"""CSR transpose (src/transpose.cu analog).

A stable argsort of column indices regroups COO entries by column; counts
become the transposed row_offsets. Static shapes (nnz preserved), so this
works both eagerly at setup time and inside jit.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..matrix import CsrMatrix, host_resident


def _transpose_host(A: CsrMatrix) -> CsrMatrix:
    """Numpy form for host-resident scalar matrices (the host-setup
    path transposes every P; eager XLA:CPU sorts cost more than the
    whole operation in numpy)."""
    ro = np.asarray(A.row_offsets)
    cols = np.asarray(A.col_indices)
    vals = np.asarray(A.values)
    row_ids = np.repeat(np.arange(A.num_rows, dtype=np.int32), np.diff(ro))
    order = np.argsort(cols, kind="stable")
    counts = np.bincount(cols, minlength=A.num_cols)
    row_offsets = np.zeros(A.num_cols + 1, np.int32)
    np.cumsum(counts, out=row_offsets[1:])
    return CsrMatrix(row_offsets=row_offsets, col_indices=row_ids[order],
                     values=vals[order], num_rows=A.num_cols,
                     num_cols=A.num_rows)


def transpose(A: CsrMatrix) -> CsrMatrix:
    if not A.is_block and not A.has_external_diag and host_resident(
            A.row_offsets, A.col_indices, A.values):
        return _transpose_host(A)
    row_ids, cols, vals = A.coo()
    order = jnp.argsort(cols, stable=True)
    new_rows = cols[order]
    new_cols = row_ids[order]
    new_vals = vals[order]
    if A.is_block:
        new_vals = jnp.swapaxes(new_vals, -1, -2)
    counts = jnp.bincount(new_rows, length=A.num_cols)
    row_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    out = CsrMatrix(row_offsets=row_offsets, col_indices=new_cols,
                    values=new_vals, num_rows=A.num_cols, num_cols=A.num_rows,
                    block_dimx=A.block_dimy, block_dimy=A.block_dimx)
    if A.has_external_diag:
        d = A.diag
        if A.is_block:
            d = jnp.swapaxes(d, -1, -2)
        out = CsrMatrix(row_offsets=out.row_offsets,
                        col_indices=out.col_indices, values=out.values,
                        diag=d, num_rows=out.num_rows, num_cols=out.num_cols,
                        block_dimx=out.block_dimx, block_dimy=out.block_dimy)
    return out
