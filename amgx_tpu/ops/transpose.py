"""CSR transpose (src/transpose.cu analog).

A stable argsort of column indices regroups COO entries by column; counts
become the transposed row_offsets. Static shapes (nnz preserved), so this
works both eagerly at setup time and inside jit.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..matrix import CsrMatrix


def transpose(A: CsrMatrix) -> CsrMatrix:
    row_ids, cols, vals = A.coo()
    order = jnp.argsort(cols, stable=True)
    new_rows = cols[order]
    new_cols = row_ids[order]
    new_vals = vals[order]
    if A.is_block:
        new_vals = jnp.swapaxes(new_vals, -1, -2)
    counts = jnp.bincount(new_rows, length=A.num_cols)
    row_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    out = CsrMatrix(row_offsets=row_offsets, col_indices=new_cols,
                    values=new_vals, num_rows=A.num_cols, num_cols=A.num_rows,
                    block_dimx=A.block_dimy, block_dimy=A.block_dimx)
    if A.has_external_diag:
        d = A.diag
        if A.is_block:
            d = jnp.swapaxes(d, -1, -2)
        out = CsrMatrix(row_offsets=out.row_offsets,
                        col_indices=out.col_indices, values=out.values,
                        diag=d, num_rows=out.num_rows, num_cols=out.num_cols,
                        block_dimx=out.block_dimx, block_dimy=out.block_dimy)
    return out
