"""Matrix-free stencil operators for constant-coefficient GEO levels.

A GEO hierarchy built from a constant-coefficient grid operator (the
structured-gallery Poisson family and everything the structured
Galerkin pair-sum derives from it) stores a DIA value slab that is
pure redundancy: every diagonal holds ONE scalar repeated across its
in-grid rows and zeros where the geometric shift exits the grid. On a
memory-bound TPU that slab is the LARGEST stream in every fused
smoother/residual kernel — k value floats per output element versus
~2 vector floats — so dropping it roughly halves the solve-phase HBM
traffic and removes the O(nnz) term from the operator's solve-data
footprint (O(levels) coefficient vectors remain).

This module is the matrix-free core:

- `StencilOperator`: the solve-data payload — a (k,) coefficient
  vector plus static geometry (offsets, grid shifts, grid shape) and
  the smoother's diagonal-inverse mode. Registered as a pytree so it
  rides solve_data like any other leaf; the coefficients are the only
  device data.
- `detect_stencil`: the setup-time constant-coefficient check — one
  jitted compare per level (every in-grid entry equals its diagonal's
  anchor value, every off-grid entry is zero; the anchor row is the
  first row where the shift is in-grid, so the check subsumes the
  GEO wrap check) and one tiny transfer (a bool + k scalars).
- XLA composes (`stencil_spmv`, `stencil_fused_smooth`, the transfer
  forms): masked shifted adds `y = sum_t where(ok_t, c_t * shift(x)),
  0)` — the f64 / batched / non-TPU route, and the route the paired
  CPU bench measures. The per-offset masks are the same static-bound
  grid comparisons the Pallas kernels evaluate in-register
  (ops/pallas_spmv.py `_mf_*` helpers).
- Pallas dispatch: the fused kernels' `coeffs` mode reads the k
  scalars from SMEM and synthesizes the value rows from the masks, so
  the A-operand stream (and its VMEM window) vanishes; plan math in
  `dia_smooth_plan(..., coeffs=True)` and friends.
- `stencil_dia_vals` / `stencil_matrix`: in-trace materialization of
  the equivalent DIA slab — the escape hatch for consumers that
  genuinely need a matrix (residual monitoring, K-cycle coarse SpMV,
  diagnostics probes), costing VPU work instead of resident HBM.

Routing policy lives in amg/hierarchy.py (`matrix_free=auto|0|1`):
variable-coefficient operators fail the detector and keep the slab
path; `0` never calls the detector, so the slab build is bit-for-bit
untouched.
"""
from __future__ import annotations

import collections
import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import pallas_spmv as _ps

# Hashable static twin of a StencilOperator (everything but the
# coefficients) — the lru/jit cache key for the kernel factories and
# custom_vmap wrappers. `dinv` is None | "jacobi" | "l1";
# `diag_rank` is the index of offset 0 (-1 when absent).
StencilSpec = collections.namedtuple(
    "StencilSpec", "offsets shifts shape n dinv diag_rank")


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["coeffs"],
    meta_fields=["offsets", "shifts", "shape", "num_rows", "dinv_mode",
                 "diag_rank"],
)
@dataclasses.dataclass(frozen=True)
class StencilOperator:
    """Constant-coefficient grid operator: A[i, i+offsets[t]] =
    coeffs[t] wherever the grid shift stays in-grid, 0 elsewhere.
    The ONLY device payload is `coeffs` (k,) — O(levels) operator
    memory across a hierarchy instead of O(nnz)."""

    coeffs: jax.Array                  # (k,)
    offsets: tuple                     # linear DIA offsets, ascending
    shifts: tuple                      # ((dx, dy, dz),) per offset
    shape: tuple                       # (nx, ny, nz), x fastest
    num_rows: int
    dinv_mode: Optional[str] = None    # None | "jacobi" | "l1"
    diag_rank: int = -1

    @property
    def k(self) -> int:
        return len(self.offsets)

    def spec(self) -> StencilSpec:
        return StencilSpec(self.offsets, self.shifts, self.shape,
                           self.num_rows, self.dinv_mode, self.diag_rank)


def _anchor_index(shift, shape) -> int:
    """First linear row index where `shift` stays in-grid — the row the
    detector reads each diagonal's candidate coefficient from."""
    nx, ny, _nz = shape
    dx, dy, dz = shift
    return (max(0, -dz) * ny + max(0, -dy)) * nx + max(0, -dx)


@functools.partial(jax.jit, static_argnames=("shifts", "shape"))
def stencil_candidate(vals2d, shifts, shape):
    """(is_const, coeffs) for a (k, n) DIA value table: coeffs[t] is
    the anchor-row value of diagonal t; is_const is True iff every
    in-grid entry equals it AND every off-grid entry is zero (which
    subsumes the GEO wrap check — a wrapped nonzero sits off-grid)."""
    nx, ny, nz = shape
    n = vals2d.shape[1]
    ix = jnp.arange(n, dtype=jnp.int32)
    gx = ix % nx
    gy = (ix // nx) % ny
    gz = ix // (nx * ny)
    coeffs, flags = [], []
    for t, (dx, dy, dz) in enumerate(shifts):
        ok = ((gx + dx >= 0) & (gx + dx < nx) & (gy + dy >= 0)
              & (gy + dy < ny) & (gz + dz >= 0) & (gz + dz < nz))
        c = vals2d[t, _anchor_index((dx, dy, dz), shape)]
        coeffs.append(c)
        flags.append(jnp.all(jnp.where(ok, vals2d[t] == c,
                                       vals2d[t] == 0)))
    return jnp.stack(flags).all(), jnp.stack(coeffs)


def stencil_shifts(offsets, shape):
    """Per-offset (dx, dy, dz) grid shifts, or None when any offset is
    not a small stencil shift of `shape`."""
    from ..amg.aggregation.galerkin import _decompose
    nx, ny, nz = shape
    shifts = []
    for d in offsets:
        g = _decompose(int(d), nx, ny, nz)
        if g is None:
            return None
        shifts.append(g)
    return tuple(shifts)


def detect_stencil(A, dinv_mode: Optional[str] = None,
                   coeffs_hint=None):
    """StencilOperator for a constant-coefficient DIA grid operator,
    or None (variable coefficients, no DIA/grid annotation, blocks,
    external diagonals, non-stencil offsets). One jitted compare +
    one tiny transfer per level. `coeffs_hint` (a (k,) device array,
    e.g. from GeoRapPlan.coarse_coeffs) skips the extraction and only
    runs the constancy compare against it."""
    if getattr(A, "dia_offsets", None) is None \
            or getattr(A, "dia_vals", None) is None \
            or getattr(A, "grid_shape", None) is None \
            or A.is_block or A.has_external_diag \
            or A.num_rows != A.num_cols:
        return None
    shape = tuple(int(s) for s in A.grid_shape)
    if len(shape) != 3 or int(np.prod(shape)) != A.num_rows:
        return None
    shifts = stencil_shifts(A.dia_offsets, shape)
    if shifts is None:
        return None
    k = len(A.dia_offsets)
    vals2d = A.dia_vals.reshape(k, -1)[:, :A.num_rows]
    ok, coeffs = stencil_candidate(vals2d, shifts, shape)
    if coeffs_hint is not None:
        coeffs = coeffs_hint
    if not bool(ok):
        return None
    offsets = tuple(int(d) for d in A.dia_offsets)
    return StencilOperator(
        coeffs=coeffs, offsets=offsets, shifts=shifts, shape=shape,
        num_rows=int(A.num_rows), dinv_mode=dinv_mode,
        diag_rank=offsets.index(0) if 0 in offsets else -1)


def mf_slim(A):
    """Solve-phase view of a matrix-free level's operator: the SpMV
    slim form with the DIA value slab dropped entirely. The result
    supports NOTHING by itself — every solve-phase consumer must route
    through the level's StencilOperator (or `stencil_matrix`); a stray
    spmv() against it fails loudly instead of serving garbage."""
    s = A.slim_for_spmv() if hasattr(A, "slim_for_spmv") else A
    if getattr(s, "dia_vals", None) is None:
        return s
    return dataclasses.replace(s, dia_vals=None)


# ---------------------------------------------------------------------------
# XLA masked-coefficient forms (vector layout)
# ---------------------------------------------------------------------------


def _vec_masks(spec):
    """Per-offset in-grid masks on the (n,) vector layout — the same
    static-bound comparisons the Pallas coeffs mode evaluates on its
    (rows, 128) windows."""
    nx, ny, nz = spec.shape
    ix = jnp.arange(spec.n, dtype=jnp.int32)
    gx = ix % nx
    gy = (ix // nx) % ny
    gz = ix // (nx * ny)
    masks = []
    for (dx, dy, dz) in spec.shifts:
        ok = None

        def conj(a, b):
            return b if a is None else a & b

        if dx < 0:
            ok = conj(ok, gx >= -dx)
        if dx > 0:
            ok = conj(ok, gx < nx - dx)
        if dy < 0:
            ok = conj(ok, gy >= -dy)
        if dy > 0:
            ok = conj(ok, gy < ny - dy)
        if dz < 0:
            ok = conj(ok, gz >= -dz)
        if dz > 0:
            ok = conj(ok, gz < nz - dz)
        masks.append(ok)          # None == everywhere in-grid
    return masks


def _apply_vec(spec, coeffs, x):
    """y = A x as masked shifted adds — no materialized values."""
    masks = _vec_masks(spec)
    y = jnp.zeros_like(x)
    zero = jnp.zeros((), x.dtype)
    for t, d in enumerate(spec.offsets):
        xs = jnp.roll(x, -d) if d else x
        term = coeffs[t].astype(x.dtype) * xs
        y = y + (term if masks[t] is None
                 else jnp.where(masks[t], term, zero))
    return y


def _dinv_vec(spec, coeffs, dtype):
    """The smoother's diagonal-inverse vector synthesized from the
    coefficients: matches safe_recip(diagonal) ("jacobi") or
    safe_recip(l1_strengthened_diag) ("l1") on the materialized
    matrix; None when the smoother carries no dinv (Chebyshev)."""
    if spec.dinv is None:
        return None
    c0 = coeffs[spec.diag_rank].astype(dtype)
    if spec.dinv == "jacobi":
        den = jnp.full((spec.n,), c0, dtype)
    else:                               # "l1"
        masks = _vec_masks(spec)
        l1 = jnp.zeros((spec.n,), dtype)
        for t in range(len(spec.offsets)):
            if t == spec.diag_rank:
                continue
            a = jnp.abs(coeffs[t].astype(dtype))
            l1 = l1 + (jnp.full((spec.n,), a, dtype)
                       if masks[t] is None
                       else jnp.where(masks[t], a, 0))
        den = c0 + jnp.sign(c0) * l1
    return jnp.where(den == 0, jnp.zeros((), dtype),
                     1 / jnp.where(den == 0, jnp.ones((), dtype), den))


def stencil_spmv(st: StencilOperator, x):
    """y = A x from coefficients only (all dtypes, all backends)."""
    return _apply_vec(st.spec(), st.coeffs, x)


def _xla_smooth(spec, coeffs, taus, b, x, with_residual):
    """Damped-relaxation sweeps + optional residual, accumulated at
    the kernel's compute dtype (f32 for bf16 vectors) so the XLA and
    Pallas routes agree to rounding."""
    cdt = _ps.compute_dtype(x.dtype)
    xs = x.astype(cdt)
    bs = b.astype(cdt)
    cc = coeffs.astype(cdt)
    dinv = _dinv_vec(spec, cc, cdt)
    for t in range(int(taus.shape[0])):
        corr = taus[t].astype(cdt) * (bs - _apply_vec(spec, cc, xs))
        if dinv is not None:
            corr = corr * dinv
        xs = xs + corr
    y = xs.astype(x.dtype)
    if with_residual:
        r = bs - _apply_vec(spec, cc, xs)
        return y, r.astype(x.dtype)
    return y


def _xla_restrict(spec, coeffs, taus, b, x, ctab, nc):
    """Smooth + unit-weight child-gather restriction (the aggregation
    transfer slab's XLA twin)."""
    y, r = _xla_smooth(spec, coeffs, taus, b, x, True)
    cdt = _ps.compute_dtype(x.dtype)
    rf = r.astype(cdt)
    bc = jnp.zeros((ctab.shape[1] * ctab.shape[2],), cdt)
    for j in range(ctab.shape[0]):
        idx = ctab[j].reshape(-1)
        valid = idx >= 0
        g = jnp.take(rf, jnp.where(valid, idx, 0))
        bc = bc + jnp.where(valid, g, jnp.zeros((), cdt))
    return y, bc[:nc].astype(x.dtype)


def _xla_corr(spec, coeffs, taus, b, x, xc, aggc):
    """Correction prologue (x += xc[agg]) + smooth."""
    cdt = _ps.compute_dtype(x.dtype)
    valid = aggc >= 0
    corr = jnp.take(xc.astype(cdt), jnp.where(valid, aggc, 0))
    xs = x.astype(cdt) + jnp.where(valid, corr, jnp.zeros((), cdt))
    return _xla_smooth(spec, coeffs, taus, b, xs.astype(x.dtype),
                       False)


# ---------------------------------------------------------------------------
# dispatch (Pallas coeffs mode with XLA fallback under one custom_vmap)
# ---------------------------------------------------------------------------


def _runtime_on() -> bool:
    return jax.default_backend() == "tpu" or _ps._FORCE_INTERPRET


def _dtype_ok(x_dtype) -> bool:
    return jnp.dtype(x_dtype).name in _ps.SMOOTH_DTYPES


def stencil_smooth_supported(spec, x_dtype, n_steps: int,
                             with_residual: bool) -> bool:
    """Trace-time gate for the fused coeffs-mode smoother kernel."""
    if not _runtime_on() or not _dtype_ok(x_dtype):
        return False
    return _ps.dia_smooth_plan(
        spec.offsets, len(spec.offsets), spec.n, n_steps, with_residual,
        itemsize=jnp.dtype(x_dtype).itemsize, coeffs=True) is not None


def stencil_restrict_supported(spec, x_dtype, n_steps: int,
                               xfer) -> bool:
    if xfer is None or xfer.cwt is not None or not _runtime_on() \
            or not _dtype_ok(x_dtype):
        return False
    return _ps.dia_restrict_plan(
        spec.offsets, len(spec.offsets), spec.n, n_steps, xfer.m,
        xfer.windows, itemsize=jnp.dtype(x_dtype).itemsize,
        coeffs=True) is not None


def stencil_prolong_supported(spec, x_dtype, n_steps: int,
                              xfer) -> bool:
    if xfer is None or xfer.ptab is not None or not _runtime_on() \
            or not _dtype_ok(x_dtype):
        return False
    return _ps.dia_prolong_plan(
        spec.offsets, len(spec.offsets), spec.n, n_steps, xfer.windows,
        itemsize=jnp.dtype(x_dtype).itemsize, coeffs=True) is not None


@functools.lru_cache(maxsize=None)
def _smooth_fn(spec, with_residual: bool):
    """custom_vmap-wrapped matrix-free smoother for one static spec:
    the primal runs the fused coeffs-mode Pallas kernel when supported
    and the XLA masked compose otherwise (f64, CPU, oversized plans);
    any vmapped batch (batched coefficients AND plain multi-RHS) takes
    the vmapped XLA compose — the masks broadcast, so no per-system
    value stream ever materializes."""
    tu = jax.tree_util

    @jax.custom_batching.custom_vmap
    def call(coeffs, taus, b, x):
        n_steps = int(taus.shape[0])
        if stencil_smooth_supported(spec, x.dtype, n_steps,
                                    with_residual):
            return _ps._dia_stencil_smooth_call(
                coeffs, taus, b, x, spec, with_residual,
                interpret=_ps._FORCE_INTERPRET)
        return _xla_smooth(spec, coeffs, taus, b, x, with_residual)

    @call.def_vmap
    def _rule(axis_size, in_batched, coeffs, taus, b, x):
        axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                     for ib in in_batched)
        y = jax.vmap(
            lambda c_, t_, b_, x_: _xla_smooth(spec, c_, t_, b_, x_,
                                               with_residual),
            in_axes=axes, axis_size=axis_size)(coeffs, taus, b, x)
        return y, ((True, True) if with_residual else True)

    return call


@functools.lru_cache(maxsize=None)
def _restrict_fn(spec):
    tu = jax.tree_util

    @jax.custom_batching.custom_vmap
    def call(coeffs, taus, b, x, xfer):
        return _ps._dia_stencil_smooth_restrict_call(
            coeffs, taus, b, x, xfer, spec,
            interpret=_ps._FORCE_INTERPRET)

    @call.def_vmap
    def _rule(axis_size, in_batched, coeffs, taus, b, x, xfer):
        axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                     for ib in in_batched)
        y = jax.vmap(
            lambda c_, t_, b_, x_, xf_: _xla_restrict(
                spec, c_, t_, b_, x_, xf_.ctab, xf_.nc),
            in_axes=axes, axis_size=axis_size)(coeffs, taus, b, x,
                                               xfer)
        return y, (True, True)

    return call


def _xb_dot(y, b):
    """XLA twin of the x'.b dot epilogue (cycle-borne r.z),
    accumulation-dtype like the kernel's f32 partials."""
    cdt = _ps.compute_dtype(y.dtype)
    return jnp.vdot(y.astype(cdt), b.astype(cdt))


@functools.lru_cache(maxsize=None)
def _corr_fn(spec, with_dot: bool = False):
    tu = jax.tree_util
    ob = (True, True) if with_dot else True

    @jax.custom_batching.custom_vmap
    def call(coeffs, taus, b, x, xc, xfer):
        return _ps._dia_stencil_prolong_smooth_call(
            coeffs, taus, b, x, xc, xfer, spec, with_dot=with_dot,
            interpret=_ps._FORCE_INTERPRET)

    @call.def_vmap
    def _rule(axis_size, in_batched, coeffs, taus, b, x, xc, xfer):
        axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                     for ib in in_batched)

        rows = max(1, -(-spec.n // _ps.LANES))
        aqf = _ps.transfer_quota_rows(spec.offsets, spec.n)[0]

        def one(c_, t_, b_, x_, xc_, xf_):
            # content region of the quota-padded aggregate-id slab
            aggc = jax.lax.slice_in_dim(
                xf_.atab, aqf, aqf + rows, 1, 0).reshape(-1)[:spec.n]
            y_ = _xla_corr(spec, c_, t_, b_, x_, xc_, aggc)
            return (y_, _xb_dot(y_, b_)) if with_dot else y_

        y = jax.vmap(one, in_axes=axes, axis_size=axis_size)(
            coeffs, taus, b, x, xc, xfer)
        return y, ob

    return call


def stencil_fused_smooth(st: StencilOperator, taus, b, x,
                         with_residual=True):
    """Matrix-free smoother dispatch: x' (and r) after len(taus)
    damped sweeps. ALWAYS produces a result — there is no slab to fall
    back to. One fused coeffs-mode pallas_call when the schedule fits
    the plan; oversized schedules chain the largest supported fused
    sub-calls (each a single pass over b/x — A contributes no stream
    at all); everything else takes the XLA masked compose."""
    spec = st.spec()
    coeffs = st.coeffs
    cdt = _ps.compute_dtype(x.dtype)
    taus = jnp.asarray(taus, cdt)
    n_steps = int(taus.shape[0])
    if n_steps < 1:
        if with_residual:
            cc = coeffs.astype(cdt)
            r = b.astype(cdt) - _apply_vec(spec, cc, x.astype(cdt))
            return x, r.astype(x.dtype)
        return x

    def sup(c, wr):
        return stencil_smooth_supported(spec, x.dtype, c, wr)

    if sup(n_steps, with_residual) or not sup(1, False):
        # one fused call, or no fused plan at all (XLA primal)
        return _smooth_fn(spec, with_residual)(coeffs, taus, b, x)
    sizes = [c for c in range(min(n_steps, _ps.SMOOTH_MAX_APPS), 0, -1)
             if sup(c, False)]
    tail = 0
    if with_residual:
        for c in range(min(n_steps, _ps.SMOOTH_MAX_APPS - 1), 0, -1):
            if sup(c, True):
                tail = c
                break
    done = 0
    while n_steps - done - tail > 0:
        rem = n_steps - done - tail
        take = next((c for c in sizes if c <= rem), None)
        if take is None:
            tail = 0
            continue
        x = _smooth_fn(spec, False)(coeffs, taus[done:done + take],
                                    b, x)
        done += take
    if not with_residual:
        return x
    if tail:
        return _smooth_fn(spec, True)(coeffs, taus[done:], b, x)
    cc = coeffs.astype(cdt)
    r = b.astype(cdt) - _apply_vec(spec, cc, x.astype(cdt))
    return x, r.astype(x.dtype)


def stencil_smooth_restrict(st: StencilOperator, taus, b, x, xfer):
    """Matrix-free presmooth + restriction epilogue: (x', bc), or None
    when no fused transfer plan applies (the caller composes
    stencil_fused_smooth + the level's restriction)."""
    if xfer is None or xfer.ptab is not None or xfer.cwt is not None:
        return None
    spec = st.spec()
    taus = jnp.asarray(taus, _ps.compute_dtype(x.dtype))
    n_steps = int(taus.shape[0])
    if n_steps < 1:
        return None
    if stencil_restrict_supported(spec, x.dtype, n_steps, xfer):
        return _restrict_fn(spec)(st.coeffs, taus, b, x, xfer)
    tail = next((c for c in range(
        min(n_steps - 1, _ps.SMOOTH_MAX_APPS - 1), 0, -1)
        if stencil_restrict_supported(spec, x.dtype, c, xfer)), 0)
    if not tail:
        return None
    head = stencil_fused_smooth(st, taus[:n_steps - tail], b, x,
                                with_residual=False)
    return _restrict_fn(spec)(st.coeffs, taus[n_steps - tail:], b,
                              head, xfer)


def stencil_corr_smooth(st: StencilOperator, taus, b, x, xc, xfer,
                        want_dot: bool = False):
    """Matrix-free prolongation/correction prologue + postsmooth: x'
    starting from x + P xc, or None when no fused transfer plan
    applies. With want_dot, returns (x', dot) where dot is the x'.b
    epilogue (the cycle-borne r.z); the head-chunked route declines
    the dot — returns (x', None) — since only the final application
    could carry it and that is the plain smoother kernel."""
    if xfer is None or xfer.ptab is not None:
        return None
    spec = st.spec()
    taus = jnp.asarray(taus, _ps.compute_dtype(x.dtype))
    n_steps = int(taus.shape[0])
    if n_steps < 1:
        return None
    if stencil_prolong_supported(spec, x.dtype, n_steps, xfer):
        return _corr_fn(spec, want_dot)(st.coeffs, taus, b, x, xc, xfer)
    head = next((c for c in range(
        min(n_steps - 1, _ps.SMOOTH_MAX_APPS), 0, -1)
        if stencil_prolong_supported(spec, x.dtype, c, xfer)), 0)
    if not head:
        return None
    x = _corr_fn(spec)(st.coeffs, taus[:head], b, x, xc, xfer)
    x = stencil_fused_smooth(st, taus[head:], b, x,
                             with_residual=False)
    return (x, None) if want_dot else x


# ---------------------------------------------------------------------------
# Krylov shell fusion: coeffs-mode SpMV + dot twin
# ---------------------------------------------------------------------------


def stencil_spmv_dot_supported(spec, x_dtype) -> bool:
    """Trace-time gate for the coeffs-mode SpMV+dot shell kernel: the
    slab gate's VMEM model minus the vanished values stream, plus the
    mask/coordinate working set."""
    if not _runtime_on() or not _dtype_ok(x_dtype):
        return False
    k = len(spec.offsets)
    left, halo_rows, br = _ps._layout(spec.offsets, k, spec.n)
    ib = jnp.dtype(x_dtype).itemsize
    win = br + halo_rows
    vmem = 2 * 2 * win * _ps.LANES * ib \
        + 2 * 3 * br * _ps.LANES * ib \
        + _ps._MF_WORK_ROWS * br * _ps.LANES * 4
    if ib < 4:
        vmem += (2 * win + 2 * br) * _ps.LANES * 4
    return vmem <= _ps._VMEM_BUDGET + 4 * 1024 * 1024


def _xla_spmv_dot(spec, coeffs, p, z, beta, d, self_dot):
    """Unfused masked-coefficient compose of the shell kernel — the
    f64 / batched route; the dots are plain vdots, so the f64 parity
    reference is the exact unfused arithmetic."""
    if z is not None:
        p = (z + beta * p).astype(p.dtype)
    ap = _apply_vec(spec, coeffs, p)
    dvec = p if d is None else d
    out = (ap, jnp.vdot(dvec, ap)) if z is None \
        else (p, ap, jnp.vdot(dvec, ap))
    if self_dot:
        out = out + (jnp.vdot(ap, ap),)
    return out


@functools.lru_cache(maxsize=None)
def _spmv_pdot_mf_fn(spec):
    tu = jax.tree_util

    @jax.custom_batching.custom_vmap
    def call(coeffs, p, z, beta):
        if stencil_spmv_dot_supported(spec, p.dtype):
            return _ps._dia_spmv_dot_call(
                None, p, z, beta, None, spec.offsets, spec.n,
                mf=spec, coeffs=coeffs,
                interpret=_ps._FORCE_INTERPRET)
        return _xla_spmv_dot(spec, coeffs, p, z, beta, None, False)

    @call.def_vmap
    def _rule(axis_size, in_batched, coeffs, p, z, beta):
        # no value stream exists to share, so every batch (coefficient
        # or vector) takes the vmapped masked compose
        axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                     for ib in in_batched)
        fn = lambda c_, p_, z_, b_: _xla_spmv_dot(  # noqa: E731
            spec, c_, p_, z_, b_, None, False)
        y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(
            coeffs, p, z, beta)
        return y, (True, True, True)

    return call


@functools.lru_cache(maxsize=None)
def _spmv_ddot_mf_fn(spec, self_dot: bool):
    tu = jax.tree_util
    ob = (True,) * (3 if self_dot else 2)

    @jax.custom_batching.custom_vmap
    def call(coeffs, p, d):
        if stencil_spmv_dot_supported(spec, p.dtype):
            return _ps._dia_spmv_dot_call(
                None, p, None, None, d, spec.offsets, spec.n,
                self_dot=self_dot, mf=spec, coeffs=coeffs,
                interpret=_ps._FORCE_INTERPRET)
        return _xla_spmv_dot(spec, coeffs, p, None, None, d, self_dot)

    @call.def_vmap
    def _rule(axis_size, in_batched, coeffs, p, d):
        axes = tuple(tu.tree_map(lambda bb: 0 if bb else None, ib)
                     for ib in in_batched)
        fn = lambda c_, p_, d_: _xla_spmv_dot(  # noqa: E731
            spec, c_, p_, None, None, d_, self_dot)
        y = jax.vmap(fn, in_axes=axes, axis_size=axis_size)(
            coeffs, p, d)
        return y, ob

    return call


def stencil_spmv_pdot(st: StencilOperator, p, z, beta):
    """Coeffs-mode twin of ops.spmv.spmv_pdot: p' = z + beta p,
    Ap' and the LOCAL p'.Ap' scalar with NO A value stream at all
    (masks synthesized from k SMEM scalars)."""
    return _spmv_pdot_mf_fn(st.spec())(st.coeffs, p, z, beta)


def stencil_spmv_ddot(st: StencilOperator, p, d, self_dot: bool = False):
    """Coeffs-mode twin of ops.spmv.spmv_ddot: Ap and the LOCAL d.Ap
    (and Ap.Ap when `self_dot`) from the kernel epilogue."""
    return _spmv_ddot_mf_fn(st.spec(), self_dot)(st.coeffs, p, d)


# ---------------------------------------------------------------------------
# materialization escape hatch
# ---------------------------------------------------------------------------


def stencil_dia_vals(st: StencilOperator, dtype=None):
    """Traced (k, rows_pad, 128) DIA slab equivalent to the stencil —
    the escape hatch for consumers that need a matrix (residual
    monitoring, K-cycle coarse SpMV, diagnostics). Recomputed per use:
    VPU work instead of resident HBM."""
    spec = st.spec()
    dt = jnp.dtype(dtype) if dtype is not None else st.coeffs.dtype
    k = st.k
    rows_pad = _ps.dia_padded_rows(k, spec.n)
    idx = jnp.arange(rows_pad * _ps.LANES, dtype=jnp.int32)
    nx, ny, nz = spec.shape
    gx = idx % nx
    gy = (idx // nx) % ny
    gz = idx // (nx * ny)
    valid = idx < spec.n
    rows = []
    for t, (dx, dy, dz) in enumerate(spec.shifts):
        ok = (valid & (gx + dx >= 0) & (gx + dx < nx)
              & (gy + dy >= 0) & (gy + dy < ny)
              & (gz + dz >= 0) & (gz + dz < nz))
        rows.append(jnp.where(ok, st.coeffs[t].astype(dt),
                              jnp.zeros((), dt)))
    return jnp.stack(rows).reshape(k, rows_pad, _ps.LANES)


def stencil_matrix(A_slim, st: StencilOperator):
    """Rebuild a usable slim DIA matrix around materialized values
    (in-trace; pairs with `mf_slim`)."""
    return dataclasses.replace(
        A_slim, dia_vals=stencil_dia_vals(st, A_slim.dtype))


def level_operator(data):
    """The solve-phase operator of a level-data dict: matrix-free
    levels (slab dropped by `mf_slim`) rebuild it in-trace from the
    stencil payload; everything else passes through. The single entry
    amg/cycles.py routes its residual/K-cycle/diagnostics matrix
    reads through."""
    A = data.get("A")
    st = data.get("stencil")
    if st is not None and getattr(A, "dia_vals", None) is None \
            and getattr(A, "dia_offsets", None) is not None:
        return stencil_matrix(A, st)
    return A


def solve_data_stencil(data):
    """The StencilOperator of a level-data dict (level or smoother
    scope), or None."""
    st = data.get("stencil")
    if st is None:
        smd = data.get("smoother")
        if isinstance(smd, dict):
            st = smd.get("stencil")
    return st
