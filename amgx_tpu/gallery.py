"""Matrix gallery: Poisson stencils and random matrices.

Analog of the bundled CUSP gallery the reference uses as its test-fixture
backbone (include/cusp/gallery/poisson.h:55-99 — poisson5pt/7pt/9pt/27pt,
used by e.g. src/tests/fgmres_convergence_poisson.cu:33-52) and of the
random CSR generators in include/test_utils.h:541-701. Structure assembly
is host-side numpy (it is a fixture generator, not a solve-path kernel);
the returned matrices live on device.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .errors import BadParametersError
from .matrix import CsrMatrix

# stencil offsets (dx, dy, dz, coefficient-sign slot filled below)
_STENCILS = {
    "5pt": [(0, 0, 0), (-1, 0, 0), (1, 0, 0), (0, -1, 0), (0, 1, 0)],
    "7pt": [(0, 0, 0), (-1, 0, 0), (1, 0, 0), (0, -1, 0), (0, 1, 0),
            (0, 0, -1), (0, 0, 1)],
    "9pt": [(dx, dy, 0) for dy in (-1, 0, 1) for dx in (-1, 0, 1)],
    "27pt": [(dx, dy, dz) for dz in (-1, 0, 1) for dy in (-1, 0, 1)
             for dx in (-1, 0, 1)],
}


def poisson(points: str, nx: int, ny: int = 1, nz: int = 1,
            dtype=np.float64) -> CsrMatrix:
    """Finite-difference Poisson matrix on a regular grid with Dirichlet
    boundaries. `points` in {'5pt','7pt','9pt','27pt'}; diagonal equals the
    stencil size minus one, off-diagonals are -1 (matches
    cusp::gallery::poisson semantics)."""
    if points not in _STENCILS:
        raise BadParametersError(f"unknown poisson stencil {points!r}")
    offsets = _STENCILS[points]
    n = nx * ny * nz
    ix, iy, iz = np.meshgrid(np.arange(nx), np.arange(ny), np.arange(nz),
                             indexing="ij")
    # row index with x fastest (matches a natural lexicographic ordering)
    idx = (iz * ny + iy) * nx + ix
    rows_l, cols_l, vals_l = [], [], []
    diag_val = float(len(offsets) - 1)
    # emit the per-offset blocks in ascending (dz,dy,dx) = ascending
    # column order: ONE stable row sort then yields (row, col) order —
    # the two-key lexsort dominated gallery time at 256^3 (117M keys)
    for (dx, dy, dz) in sorted(offsets, key=lambda o: (o[2], o[1], o[0])):
        jx, jy, jz = ix + dx, iy + dy, iz + dz
        mask = ((jx >= 0) & (jx < nx) & (jy >= 0) & (jy < ny)
                & (jz >= 0) & (jz < nz))
        val = diag_val if (dx, dy, dz) == (0, 0, 0) else -1.0
        rows_l.append(idx[mask].ravel())
        cols_l.append(((jz * ny + jy) * nx + jx)[mask].ravel())
        vals_l.append(np.full(mask.sum(), val, dtype=dtype))
    rows = np.concatenate(rows_l)
    cols = np.concatenate(cols_l)
    vals = np.concatenate(vals_l)
    order = np.argsort(rows, kind="stable")
    rows, cols, vals = rows[order], cols[order], vals[order]
    counts = np.bincount(rows, minlength=n)
    row_offsets = np.zeros(n + 1, np.int32)
    np.cumsum(counts, out=row_offsets[1:])
    A = CsrMatrix.from_scipy_like(row_offsets, cols.astype(np.int32),
                                  vals, n, n)
    # structured-grid annotation: lets the GEO aggregation selector keep
    # every coarse level banded (DIA) instead of falling to gather paths
    import dataclasses
    return dataclasses.replace(A, grid_shape=(nx, ny, nz))


def poisson5pt(nx, ny, dtype=np.float64):
    return poisson("5pt", nx, ny, 1, dtype)


def poisson7pt(nx, ny, nz, dtype=np.float64):
    return poisson("7pt", nx, ny, nz, dtype)


def poisson9pt(nx, ny, dtype=np.float64):
    return poisson("9pt", nx, ny, 1, dtype)


def poisson27pt(nx, ny, nz, dtype=np.float64):
    return poisson("27pt", nx, ny, nz, dtype)


def random_matrix(n: int, max_nnz_per_row: int = 8, seed: int = 0,
                  symmetric: bool = False, diag_dominant: bool = True,
                  block_dims=(1, 1), dtype=np.float64) -> CsrMatrix:
    """Random sparse matrix with guaranteed diagonal, optionally symmetric
    and diagonally dominant (generateMatrixRandomStruct analog,
    include/test_utils.h:541-701)."""
    rng = np.random.default_rng(seed)
    rows_l, cols_l = [np.arange(n)], [np.arange(n)]       # diagonal first
    for i in range(n):
        k = rng.integers(0, max_nnz_per_row)
        if k:
            c = rng.choice(n, size=min(k, n), replace=False)
            c = c[c != i]
            rows_l.append(np.full(c.size, i))
            cols_l.append(c)
    rows = np.concatenate(rows_l)
    cols = np.concatenate(cols_l)
    if symmetric:
        rows, cols = np.concatenate([rows, cols]), np.concatenate([cols, rows])
    key = rows.astype(np.int64) * n + cols
    _, uniq = np.unique(key, return_index=True)
    rows, cols = rows[uniq], cols[uniq]
    bx, by = block_dims
    if bx * by > 1:
        vals = rng.standard_normal((rows.size, bx, by)).astype(dtype)
    else:
        vals = rng.standard_normal(rows.size).astype(dtype)
    if symmetric:
        # symmetrize: average entry (i,j) with (j,i) — blocks must also be
        # transposed so that block(i,j) == block(j,i)^T
        order = np.lexsort((cols, rows))
        order_t = np.lexsort((rows, cols))
        vt = vals[order_t]
        if bx * by > 1:
            vt = np.swapaxes(vt, -1, -2)
        vals = 0.5 * (vals[order] + vt)
        rows, cols = rows[order], cols[order]
    if diag_dominant:
        abssum = np.zeros(n, dtype)
        flat = np.abs(vals).reshape(vals.shape[0], -1).sum(-1)
        np.add.at(abssum, rows, flat)
        is_diag = rows == cols
        if bx * by > 1:
            eye = np.eye(bx, by, dtype=dtype)
            vals[is_diag] = (abssum[rows[is_diag], None, None] + 1.0) * eye
        else:
            vals[is_diag] = abssum[rows[is_diag]] + 1.0
    order = np.lexsort((cols, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    counts = np.bincount(rows, minlength=n)
    row_offsets = np.zeros(n + 1, np.int32)
    np.cumsum(counts, out=row_offsets[1:])
    return CsrMatrix.from_scipy_like(row_offsets, cols.astype(np.int32),
                                     vals, n, n,
                                     block_dims=block_dims)
