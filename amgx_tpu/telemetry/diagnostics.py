"""Convergence diagnostics: per-level cycle-stage residual norms.

The reference ships `obtain_diagnostics` / grid statistics so a user can
see WHY a hierarchy converges slowly, not just that it does; AMGCL's
profiling attributes convergence to per-level cycle stages the same way.
This module is that layer for the TPU port: an opt-in `diagnostics=1`
mode records, IN-TRACE, the residual norm at the three stages of every
level's cycle visit —

    entry            ||b_l - A_l x_in||   (what the level was handed)
    post_presmooth   ||b_l - A_l x'||     (after the presmoother)
    post_correction  ||b_l - A_l (x'+P xc)||  (after the coarse-grid
                                               correction)
    post_postsmooth  ||b_l - A_l x''||    (the level's exit residual)

— and host-side derivation turns them into per-level reduction factors,
smoother effectiveness, a coarse-correction quality column, a
"bottleneck level" attribution, and an asymptotic convergence-factor
estimate from the residual-history tail. Everything lands on
`SolveReport.diagnostics`.

Execution model (the `in-trace` contract): the solve driver
(solvers/base.py `_build_solve_fn`) appends ONE instrumented multigrid
cycle — the "probe" — on the residual equation `A d = r_final` at the
END of the traced solve program, and packs the recorded norms into the
SAME stats vector the monitor already returns. So:

- zero added device->host transfers (the probe rides the one stats
  buffer);
- the probe sees the asymptotic regime (the final residual), which is
  exactly what per-level reduction factors should describe;
- it works at ANY preconditioner nesting depth (the flagship's
  REFINEMENT -> FGMRES -> AMG chain included) because it runs at the
  top level of the traced program, not inside the nested loops;
- `diagnostics=0` (the default) changes NOTHING: the driver emits a
  jaxpr identical to a build that never heard of this module
  (tests/test_diagnostics.py proves it the PR-7 way).

Cost when ON: one extra instrumented cycle per solve — each recorded
stage is a residual SpMV + an L2 reduction, so roughly 2x one cycle's
work, once per solve (NOT per iteration). The probe cycle composes the
stage boundaries explicitly (no VMEM coarse-tail megakernel, unfused
correction) so every stage exists to measure; the solve iterations
themselves keep their fused kernels either way.

Recording mechanics: the cycle recursion (amg/cycles.py) is plain
Python unrolled at trace time, so a thread-local "tape" collects the
traced norm values as the probe traces; `Recorder.pack` then turns the
tape into the traced vector appended to the stats. The tape is active
ONLY inside `capturing()` — normal cycle traces never consult it
beyond one None-check per level.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional

import numpy as np

# stage order inside each level's 4-slot group (the packed layout is
# [level][stage], levels outermost)
STAGES = ("entry", "post_presmooth", "post_correction", "post_postsmooth")
SLOTS_PER_LEVEL = len(STAGES)

_tls = threading.local()


def current():
    """The active Recorder while a probe cycle is being traced, else
    None (the gate amg/cycles.py consults — one attribute read per
    level visit, no trace effect when inactive)."""
    return getattr(_tls, "rec", None)


class Recorder:
    """Trace-time tape of (level, stage) -> residual-norm values. A
    level visited more than once per cycle (W/F shapes, K-cycle inner
    iterations) overwrites its slots, so the packed vector reports the
    LAST visit — the one whose exit residual the cycle returns."""

    def __init__(self, num_levels: int):
        self.num_levels = int(num_levels)
        self.slots: Dict[tuple, Any] = {}

    def record(self, lvl: int, stage: int, A, x, b):
        import jax.numpy as jnp

        from ..ops.spmv import residual
        r = residual(A, x, b)
        self.slots[(int(lvl), int(stage))] = jnp.sqrt(jnp.sum(r * r))

    def pack(self, dtype):
        """The tape as one traced vector, shape (4 * num_levels,);
        never-recorded slots (unreachable for the supported cycle
        shapes) pack as NaN so the host derivation can tell 'missing'
        from 'zero residual'."""
        import jax.numpy as jnp
        vals = []
        for lvl in range(self.num_levels):
            for st in range(SLOTS_PER_LEVEL):
                v = self.slots.get((lvl, st))
                vals.append(jnp.asarray(jnp.nan if v is None else v,
                                        dtype))
        if not vals:
            return jnp.zeros((0,), dtype)
        return jnp.stack(vals)


@contextlib.contextmanager
def capturing(rec: Recorder):
    prev = getattr(_tls, "rec", None)
    _tls.rec = rec
    try:
        yield rec
    finally:
        _tls.rec = prev


def slots_len(amg) -> int:
    """Packed probe length for a hierarchy (0 = no smoothed levels,
    probe skipped)."""
    return SLOTS_PER_LEVEL * len(getattr(amg, "levels", ()))


def probe_cycle(amg, amg_data, r, dtype):
    """Trace ONE instrumented multigrid cycle on the residual equation
    `A d = r` (zero initial guess) and return the packed stage-norm
    vector. Called from inside the solve driver's traced body, so the
    probe is part of the same XLA program and its outputs ride the
    packed stats. `r` is the outer system's final residual in the outer
    dtype; it is cast to the hierarchy's stored dtype (the flagship's
    AMG is f32 under an f64 outer loop) and `amg.cycle` applies any
    `amg_precision` cast on top, exactly like a real cycle."""
    import jax.numpy as jnp
    lv0 = amg.levels[0].A
    pb = r.astype(lv0.values.dtype)
    rec = Recorder(len(amg.levels))
    with capturing(rec):
        amg.cycle(amg_data, pb, jnp.zeros_like(pb))
    return rec.pack(dtype)


# ---------------------------------------------------------------------------
# host-side derivation
# ---------------------------------------------------------------------------


def _finite(v) -> Optional[float]:
    v = float(v)
    return v if np.isfinite(v) else None


def _ratio(num, den) -> Optional[float]:
    if num is None or den is None or den <= 0.0:
        return None
    r = num / den
    return r if np.isfinite(r) else None


def asymptotic_convergence_factor(res_hist, tail_window: int = 8
                                  ) -> Optional[float]:
    """Geometric mean of the residual-reduction ratios over the tail of
    the (already host-side) residual history — the standard asymptotic
    convergence-factor estimate. Block norms collapse to their max
    component (the monitored quantity). None when the history is too
    short or degenerate to estimate from."""
    if res_hist is None:
        return None
    h = np.asarray(res_hist, dtype=float)
    if h.ndim > 1:
        h = h.max(axis=tuple(range(1, h.ndim)))
    h = h[np.isfinite(h) & (h > 0.0)]
    if h.size < 3:
        return None
    tail = h[-min(tail_window + 1, h.size):]
    ratios = tail[1:] / tail[:-1]
    ratios = ratios[np.isfinite(ratios) & (ratios > 0.0)]
    if ratios.size == 0:
        return None
    return float(np.exp(np.mean(np.log(ratios))))


def derive(diag_vec, num_levels: int, res_hist=None,
           tail_window: int = 8) -> Dict[str, Any]:
    """Turn the packed probe vector into the structured diagnostics
    block `SolveReport.diagnostics` carries:

    - per-level stage norms and reduction factors
      (`presmooth_reduction`, `correction_reduction`,
      `postsmooth_reduction`, `level_reduction` = the whole visit);
    - `smoother_effectiveness` per level: geometric mean of the pre-
      and postsmoother reductions (1.0 = the smoother does nothing);
    - `bottleneck_level`: the level whose visit reduces its own
      residual LEAST (largest `level_reduction`) — where to aim a
      smoother/strength-threshold fix first;
    - `cycle_reduction`: the finest level's whole-visit factor (= one
      cycle's total effect on the probe residual);
    - `asymptotic_convergence_factor` from the residual-history tail.
    """
    diag = np.asarray(diag_vec, dtype=float).reshape(
        num_levels, SLOTS_PER_LEVEL)
    levels: List[Dict[str, Any]] = []
    bottleneck = None
    for lvl in range(num_levels):
        e, pp, pc, ps = (_finite(v) for v in diag[lvl])
        row: Dict[str, Any] = {
            "level": lvl,
            "entry_norm": e,
            "post_presmooth_norm": pp,
            "post_correction_norm": pc,
            "post_postsmooth_norm": ps,
            "presmooth_reduction": _ratio(pp, e),
            "correction_reduction": _ratio(pc, pp),
            "postsmooth_reduction": _ratio(ps, pc),
            "level_reduction": _ratio(ps, e),
        }
        sm = [r for r in (row["presmooth_reduction"],
                          row["postsmooth_reduction"]) if r is not None]
        row["smoother_effectiveness"] = (
            float(np.exp(np.mean(np.log(np.maximum(sm, 1e-300)))))
            if sm else None)
        levels.append(row)
        lr = row["level_reduction"]
        if lr is not None and (bottleneck is None or lr > bottleneck[1]):
            bottleneck = (lvl, lr)
    return {
        "stages": list(STAGES),
        "levels": levels,
        "bottleneck_level": None if bottleneck is None else bottleneck[0],
        "bottleneck_reduction":
            None if bottleneck is None else bottleneck[1],
        "cycle_reduction":
            levels[0]["level_reduction"] if levels else None,
        "asymptotic_convergence_factor":
            asymptotic_convergence_factor(res_hist, tail_window),
    }


# ---------------------------------------------------------------------------
# diagnostics -> concrete config deltas
# ---------------------------------------------------------------------------

# the doctor's hint sentences (examples/convergence_doctor.py prints
# them verbatim; several candidates may share one hint, so the doctor
# dedups in order — its output predates this mapping and must not move)
HINT_SMOOTHER = ("the smoother barely reduces the residual "
                 "there — raise sweeps/relaxation_factor or "
                 "switch smoother")
HINT_CORRECTION = ("the coarse-grid correction INCREASES the "
                   "residual — interpolation quality: lower "
                   "strength_threshold or use D2/multipass")


def suggest_config_deltas(diag: Optional[Dict[str, Any]]
                          ) -> List[Dict[str, Any]]:
    """Map a `derive()` diagnostics block to concrete config-delta
    candidates — the single source both consumers read: the
    convergence doctor prints each suggestion's `hint` (None for the
    tuner-only candidates, so its output stays the historical two
    sentences), and the serving autotuner shadow-solves each
    suggestion's `deltas`.

    Each suggestion:

        {"knob": <short tag>, "hint": <doctor sentence or None>,
         "level": <bottleneck level or None>,
         "deltas": [{"param": <registry name>, "value": ...}, ...]}

    `deltas` name registered config parameters WITHOUT scopes — the
    applier overrides the parameter wherever the live config sets it
    (else at the default scope, which every scope falls back to), so
    one mapping serves any solver-tree shape. Rules:

    - ineffective smoother at the bottleneck (effectiveness > 0.8):
      swap to JACOBI_L1 (resetting relaxation_factor — an overdamped
      factor must not ride along), or just re-damp the current one;
    - coarse-grid correction AMPLIFYING the residual (> 1.1):
      stock strength threshold, or D2 interpolation with row
      truncation (interpolation-quality levers);
    - cycle barely biting overall (asymptotic factor > 0.85): W-cycle
      (more coarse visits per fine sweep);
    - comfortable convergence (asymptotic factor < 0.35): trade slack
      for bandwidth with solve_precision=float (wall lever — shadow
      measurement decides whether the extra iterations pay for the
      halved slab bytes).
    """
    out: List[Dict[str, Any]] = []
    if not diag:
        return out
    levels = diag.get("levels") or []
    bl = diag.get("bottleneck_level")
    row = next((r for r in levels if r.get("level") == bl), None) \
        if bl is not None else None
    if row is not None:
        if (row["smoother_effectiveness"] or 0) > 0.8:
            out.append({"knob": "smoother_swap", "hint": HINT_SMOOTHER,
                        "level": bl, "deltas": [
                            {"param": "smoother", "value": "JACOBI_L1"},
                            {"param": "relaxation_factor", "value": 0.9},
                        ]})
            out.append({"knob": "relaxation", "hint": HINT_SMOOTHER,
                        "level": bl, "deltas": [
                            {"param": "relaxation_factor", "value": 0.9},
                        ]})
        if (row["correction_reduction"] or 0) > 1.1:
            out.append({"knob": "strength", "hint": HINT_CORRECTION,
                        "level": bl, "deltas": [
                            {"param": "strength_threshold",
                             "value": 0.25},
                        ]})
            out.append({"knob": "interp", "hint": HINT_CORRECTION,
                        "level": bl, "deltas": [
                            {"param": "interpolator", "value": "D2"},
                            {"param": "interp_max_elements", "value": 4},
                        ]})
    acf = diag.get("asymptotic_convergence_factor")
    if acf is not None and acf > 0.85:
        out.append({"knob": "cycle", "hint": None, "level": bl,
                    "deltas": [{"param": "cycle", "value": "W"}]})
    if acf is not None and acf < 0.35:
        out.append({"knob": "precision", "hint": None, "level": bl,
                    "deltas": [{"param": "solve_precision",
                                "value": "float"}]})
    return out
