"""Hierarchical host spans with Perfetto export.

The reference's AMGX_timer tree (src/amgx_timer.cu) keeps parent/child
timing relationships; the port's original `profiling.py` flattened them
into a name->total dict. This module restores the tree: every
`span(name)` records a (name, start, duration, depth, parent, thread)
event into a bounded process-wide buffer, alongside the flat
(calls, total) accumulator the existing `profiling.timers()` /
`timers_total()` API keeps reading — the accounted-fraction contract
(`timers_total("amg.") / wall`, PR 3) is unchanged because the amg.*
setup regions remain DISJOINT LEAF spans by construction (the span
REGISTRY below is statically linted for that by tools/check_spans.py).

Spans measure HOST wall clock. Under async dispatch that means "time
until the region's Python body returned", not device occupancy — the
honest default for orchestration spans. Set `telemetry_sync=1` (config)
or AMGX_TPU_TELEMETRY_SYNC=1 (env) to fence device work at every span
boundary so host spans bound device occupancy; this perturbs pipelining
(the overlapped level shipping, XLA async dispatch), so it is a
debugging mode, not a production default.

`export_chrome_trace(path)` writes the recorded spans as Chrome
trace-event JSON ("X" complete events, microseconds), loadable by
Perfetto / chrome://tracing — the host-side timeline that sits next to
the device timeline `profiling.start_trace` captures via jax.profiler.

REQUEST TRACING: spans (and instant `mark()` events) accept an `args`
dict; an args entry `trace=<id>` (or `traces=[ids]` for batched
stages touching several requests) tags the event with a request trace
id (`new_trace_id()`; serving mints one per ServiceTicket). The
export turns each trace id's tagged events into a Perfetto FLOW — a
connected s→t→…→f arrow chain through the tagged slices — so one
request's submit→queue→build→admit→chunk-cycles→checkpoint→finalize
path reads as a single arrow chain in the trace viewer, across
threads and (because the serving journal persists trace ids) across
service incarnations when a crash-recovered resume re-tags the
original id. `record_span()` records a span retroactively with
explicit timing (queue waits measured between submit and admission;
per-shard synthetic tracks use its `tid` override).
"""
from __future__ import annotations

import contextlib
import fnmatch
import hashlib
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# span-name registry
# ---------------------------------------------------------------------------

# Every span/trace_region name used in the package must match one of
# these fnmatch patterns (tools/check_spans.py enforces it statically).
# Patterns under ACCOUNTED_PREFIX are additionally checked to be
# pairwise non-nesting: the setup_accounted_fraction >= 0.9 contract
# sums them, so no amg.* span may ever double-count a child.
DECLARED_SPANS: Tuple[str, ...] = (
    # amg.* accounted setup leaves (disjoint by contract)
    "amg.l0_layout",
    "amg.host_pull",
    "amg.value_resetup",
    "amg.L*.selector",
    "amg.L*.strength",
    "amg.L*.cfsplit",
    "amg.L*.interp",
    "amg.L*.layoutP",
    "amg.L*.transposeR",
    "amg.L*.xfer_slabs",
    # classical device-parallel RS/HMIS first pass: runs INSIDE the
    # amg.L*.cfsplit leaf on the main thread, so it is declared
    # OUTSIDE the amg.* accounted prefix (summing both would
    # double-count the selector wall)
    "selector.device_sweep",
    "amg.L*.rap",
    # plan-split RAP (ops/spgemm.py): structure-phase plan build/lookup
    # and the fused value phase — disjoint siblings of amg.L*.rap (the
    # eager route's span), never nested inside it
    "amg.L*.rap_plan",
    "amg.L*.rap_values",
    "amg.L*.mf_detect",
    "amg.L*.galerkin",
    "amg.L*.layout",
    "amg.L*.smoother_setup",
    "amg.coarse_solver_setup",
    "amg.ship_resolve",
    "amg.device_sync",
    # overlapped ship worker (reports on its own thread; NOT summed
    # into the amg.* accounted fraction)
    "ship.cast_put",
    "ship.resolve_stragglers",
    # serving subsystem (amgx_tpu/serving/): the scheduler's cycle
    # phases + the AOT store round-trips
    "serving.step",
    "serving.admit",
    "serving.finalize",
    "serving.bucket_build",
    "serving.aot_export",
    "serving.aot_load",
    # serving fault tolerance: checkpoint/journal writes, restart
    # replay, hierarchy-structure persistence, bucket quarantine
    "serving.checkpoint",
    "serving.recover",
    "serving.quarantine",
    "serving.hstore_save",
    "serving.hstore_load",
    # request-path tracing (serving_tracing knob): per-ticket
    # lifecycle stages tagged with the ticket's trace id — submit
    # bookkeeping, shed decisions (instant), the retroactive queue
    # wait, the build the candidate ticket triggered, journal-replay
    # resume, and the terminal completion (instant; the flow chain's
    # last anchor)
    "serving.submit",
    "serving.shed",
    "serving.queue",
    "serving.build",
    "serving.resume",
    "serving.complete",
    # fleet router (serving/fleet.py): the per-request routing
    # decision — an instant event on the ticket's flow chain carrying
    # the serving replica id and route class (warm|cold|spill), the
    # cross-replica postmortem's attribution anchor
    "fleet.route",
    # fleet health (serving/health.py): every breaker/liveness
    # transition (SUSPECT, WEDGED, DEAD, OPEN/HALF_OPEN/CLOSED, DOWN,
    # DRAINING, RESTORED, PROBE) as an instant event — the Perfetto
    # view of an incident timeline
    "fleet.health.transition",
    # fleet failover (serving/fleet.py): one instant event per DOWN
    # path with its whole outcome (survivors, tickets requeued,
    # fingerprints rehomed, journal adopter + replay count, wall)
    "fleet.failover",
    # online config autotuner (serving/autotune.py): each shadow
    # solve as a real span (the idle-capacity cost is visible on the
    # timeline next to production work), each promote/demote/retire
    # verdict as an instant event — both tagged with the search's
    # trace id so the whole watch->shadow->promote chain reconstructs
    "autotune.shadow",
    "autotune.decision",
    # distributed comms/shard telemetry: one synthetic track per
    # shard in the Perfetto export (record_span with a per-shard tid)
    "shard.solve",
    # solver-tree entry points (dynamic solver names: CG.solve, ...).
    # NO catch-all patterns belong here: a `<anything>.*` entry would
    # let any typo'd two-segment name pass the static registry check
    # (telemetry's own engine spans live in the checker-exempt
    # spans.py and need no declaration)
    "*.setup",
    "*.resetup",
    "*.solve",
)

ACCOUNTED_PREFIX = "amg."


def is_declared(name: str) -> bool:
    return any(fnmatch.fnmatchcase(name, p) for p in DECLARED_SPANS)


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_tls = threading.local()
_records: List[dict] = []
_MAX_RECORDS = 100_000      # oldest half dropped past this
_flat: Dict[str, Tuple[int, float]] = {}
_t0 = time.perf_counter()   # trace epoch (ts offsets in the export)

def env_sync() -> bool:
    """The AMGX_TPU_TELEMETRY_SYNC environment toggle (read at call
    time). The root-construction latch ORs this in, so the env var
    keeps fencing on even when configs leave telemetry_sync=0."""
    return os.environ.get("AMGX_TPU_TELEMETRY_SYNC", "0") not in (
        "", "0", "false", "False")


_sync = env_sync()


def set_sync(on: bool):
    """Enable/disable device fencing at span boundaries (the
    telemetry_sync knob)."""
    global _sync
    _sync = bool(on)


def sync_enabled() -> bool:
    return _sync


def _fence():
    """Best-effort device fence so a host span bounds device occupancy.
    Backends without a synchronization surface degrade to a no-op (the
    span then measures dispatch, as documented)."""
    try:
        import jax
        for d in jax.local_devices():
            try:
                d.synchronize_all_activity()
            except Exception:
                pass
    except Exception:
        pass


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


@contextlib.contextmanager
def span(name: str, annotate: bool = True,
         args: Optional[Dict[str, Any]] = None):
    """Record one hierarchical span (and accumulate the flat timer).
    With annotate=True the region is also a jax.profiler
    TraceAnnotation, so it shows up in captured device profiles — the
    nvtxRange analog `profiling.trace_region` has always been.
    `args` attaches extra key/values to the exported event; a
    `trace`/`traces` entry additionally enrolls the span in that
    request's Perfetto flow chain (module docs)."""
    if _sync:
        _fence()
    stack = _stack()
    parent = stack[-1] if stack else None
    stack.append(name)
    t_start = time.perf_counter()
    ctx = contextlib.nullcontext()
    if annotate:
        try:
            import jax
            ctx = jax.profiler.TraceAnnotation(name)
        except Exception:
            pass
    try:
        with ctx:
            yield
    finally:
        if _sync:
            _fence()
        t_end = time.perf_counter()
        stack.pop()
        dt = t_end - t_start
        rec = {"name": name, "ts": t_start - _t0, "dur": dt,
               "depth": len(stack), "parent": parent,
               "tid": threading.get_ident()}
        if args:
            rec["args"] = dict(args)
        _commit(rec, name, dt)


def _commit(rec: dict, name: str, dt: float):
    with _lock:
        _records.append(rec)
        if len(_records) > _MAX_RECORDS:
            del _records[: _MAX_RECORDS // 2]
        calls, tot = _flat.get(name, (0, 0.0))
        _flat[name] = (calls + 1, tot + dt)


def mark(name: str, args: Optional[Dict[str, Any]] = None):
    """Record one INSTANT event (zero-duration; exported as a Chrome
    'i' event) — lifecycle points like a shed decision or a request's
    terminal completion, where a span would be noise. Shares the span
    registry (check_spans lints mark names too) and the flow-chain
    tagging via args."""
    stack = _stack()
    rec = {"name": name, "ts": time.perf_counter() - _t0, "dur": 0.0,
           "depth": len(stack), "parent": stack[-1] if stack else None,
           "tid": threading.get_ident(), "ph": "i"}
    if args:
        rec["args"] = dict(args)
    _commit(rec, name, 0.0)


def record_span(name: str, t_start: float, dur: float,
                args: Optional[Dict[str, Any]] = None,
                tid: Optional[int] = None):
    """Record a span RETROACTIVELY with explicit timing: `t_start` in
    time.perf_counter() units, `dur` in seconds. Used for intervals
    only known after the fact (a ticket's queue wait, measured when it
    is admitted) and — via the `tid` override — for synthetic tracks
    (one Perfetto track per shard: the per-shard tallies of a
    distributed solve). Flat-timer accounting matches span()."""
    rec = {"name": name, "ts": t_start - _t0, "dur": float(dur),
           "depth": 0, "parent": None,
           "tid": int(tid) if tid is not None else threading.get_ident()}
    if args:
        rec["args"] = dict(args)
    _commit(rec, name, float(dur))


# ---------------------------------------------------------------------------
# request trace ids
# ---------------------------------------------------------------------------

_trace_seq = itertools.count(1)


def new_trace_id() -> str:
    """Mint a process-unique request trace id (pid + monotone counter
    + a coarse time suffix so ids stay distinct across process
    restarts — the successor of a crashed service mints fresh ids for
    new work while journal-replayed requests keep their ORIGINAL id,
    which is what links their spans across incarnations)."""
    return (f"{os.getpid():x}-{next(_trace_seq):x}-"
            f"{int(time.time() * 1e3) & 0xFFFFFF:x}")


def records() -> List[dict]:
    """Copy of the recorded span events (oldest first)."""
    with _lock:
        return [dict(r) for r in _records]


def flat_timers() -> Dict[str, Tuple[int, float]]:
    """The flat (calls, total_seconds) view per span name — the
    accumulator `profiling.timers()` has always returned."""
    with _lock:
        return dict(_flat)


def timers_total(prefix: str) -> float:
    """Total wall seconds under span names starting with `prefix`. The
    amg.* setup regions are maintained as DISJOINT leaf spans (enforced
    by the registry above + tools/check_spans.py) precisely so
    `timers_total("amg.") / wall` is an honest accounted fraction."""
    with _lock:
        return sum(tot for name, (_c, tot) in _flat.items()
                   if name.startswith(prefix))


def reset():
    """Drop recorded spans and flat accumulations (open spans on any
    thread keep recording into the fresh buffers when they close)."""
    with _lock:
        _records.clear()
        _flat.clear()


# ---------------------------------------------------------------------------
# Perfetto / chrome://tracing export
# ---------------------------------------------------------------------------


def _flow_id(trace: str) -> int:
    """Stable positive int flow id for a request trace id (Chrome
    flow events bind on (cat, name, id); the id must survive export
    across processes, so it is a digest, not an enumeration)."""
    return int.from_bytes(
        hashlib.blake2b(str(trace).encode(), digest_size=6).digest(),
        "big")


def trace_track(trace: str, base: int = 2_000_000) -> int:
    """Synthetic per-request track id for RETROACTIVE request-lane
    spans (the serving.queue wait): recorded on the admitting
    scheduler thread's real tid they would partially overlap its open
    cycle slices, which the Chrome trace format forbids (same-track
    slices must nest). One derived track per trace id keeps every
    request's lane self-consistent; a digest collision between two
    concurrent requests costs only a cosmetic overlap on a synthetic
    lane, never a corrupt scheduler track."""
    return base + _flow_id(str(trace)) % 1_000_000


def chrome_trace_events() -> List[dict]:
    """The recorded spans as Chrome trace-event events — 'X' complete
    slices (instant marks as 'i') with ts/dur in microseconds from the
    trace epoch, one track per host thread. Nesting is positional
    (Perfetto stacks overlapping events on a track), so parent linkage
    needs no explicit ids.

    Events whose args carry a request trace id (`trace=<id>` /
    `traces=[ids]`) additionally yield Perfetto FLOW events: per trace
    id, the tagged events sorted by start time become one s→t→…→f
    chain, each flow anchor emitted at its slice's start on the same
    pid/tid so it binds to that slice — the single connected arrow
    chain per request the serving layer's tracing promises. Flow
    anchors only bind to SLICES, so a trace-tagged instant mark (a
    shed decision, the terminal serving.complete) exports as a
    1-microsecond 'X' slice instead of an unbindable 'i' event —
    untagged marks stay true instants."""
    evs = []
    flows: Dict[str, List[Tuple[float, int, int]]] = {}
    for r in records():
        args = {"depth": r["depth"], "parent": r["parent"]}
        extra = r.get("args") or {}
        args.update(extra)
        ph = r.get("ph", "X")
        tr = extra.get("trace")
        tagged = ([tr] if tr else []) + [
            t for t in (extra.get("traces") or ()) if t]
        if ph == "i" and tagged:
            ph = "X"                 # bindable micro-slice (see docs)
        ev = {
            "name": r["name"],
            "cat": (ACCOUNTED_PREFIX.rstrip(".")
                    if r["name"].startswith(ACCOUNTED_PREFIX)
                    else r["name"].split(".", 1)[0]),
            "ph": ph,
            "ts": round(r["ts"] * 1e6, 3),
            "dur": max(round(r["dur"] * 1e6, 3),
                       1.0 if tagged else 0.0),
            "pid": os.getpid(),
            "tid": r["tid"],
            "args": args,
        }
        if ph == "i":
            ev["s"] = "t"            # thread-scoped instant
            del ev["dur"]
        evs.append(ev)
        for t in tagged:
            flows.setdefault(str(t), []).append(
                (ev["ts"], ev["pid"], ev["tid"]))
    for trace, anchors in flows.items():
        if len(anchors) < 2:
            continue                 # nothing to connect
        anchors.sort()
        fid = _flow_id(trace)
        last = len(anchors) - 1
        for i, (ts, pid, tid) in enumerate(anchors):
            fe = {
                "name": "request",
                "cat": "trace.flow",
                "ph": "s" if i == 0 else ("f" if i == last else "t"),
                "id": fid,
                "ts": ts,
                "pid": pid,
                "tid": tid,
                "args": {"trace": trace},
            }
            if fe["ph"] == "f":
                fe["bp"] = "e"       # bind to the ENCLOSING slice
            evs.append(fe)
    return evs


def export_chrome_trace(path: str) -> int:
    """Write the recorded spans as a Perfetto-loadable trace-event JSON
    file; returns the number of events written."""
    evs = chrome_trace_events()
    payload = {
        "traceEvents": evs,
        "displayTimeUnit": "ms",
        "otherData": {"source": "amgx_tpu.telemetry.spans"},
    }
    with span("telemetry.export", annotate=False):
        with open(path, "w") as f:
            json.dump(payload, f)
            f.write("\n")
    return len(evs)
