"""Process-wide counter/gauge registry.

The reference exposes its runtime behavior through scattered printf
tables; a serving deployment needs the numbers a scrape endpoint or a
periodic dump can read: cache hit rates, retrace counts, batcher
occupancy, memory watermarks. This module is that registry — one flat,
thread-safe, process-wide namespace of declared metrics.

Design rules:

- every metric is DECLARED up front (name + kind + doc) so the registry
  doubles as the documentation of what the library measures; an
  undeclared name raises with a did-you-mean suggestion instead of
  silently forking a typo'd time series;
- counters are monotonic within a process (`inc`); gauges are
  last-value (`set_gauge`) or high-water (`max_gauge`); histograms are
  fixed-bucket-edge distributions (`observe`) with optional labels
  (per-tenant latency series) and bucket-interpolated quantiles
  (`quantile`) so p50/p99 are live service state, not bench-only;
- recording is a dict update under one lock — cheap enough to stay
  unconditional (the `telemetry` config knob gates report construction
  and span fencing, not counter arithmetic);
- `snapshot()` returns a plain dict (JSON-ready) of every metric that
  has been touched, plus zeros for declared-but-untouched counters and
  empty histograms so a dump always has a stable key set;
- `to_openmetrics()` renders the whole registry as an OpenMetrics text
  exposition (`# EOF`-terminated, Prometheus-compatible) — the payload
  a /metrics scrape endpoint serves; reachable from the C API as
  `AMGX_read_metrics_openmetrics`.

Instrumented sites (see the declarations below for the full catalog):
the GEO Galerkin structure-cache (amg/aggregation/galerkin.py), the
setup/resetup routing (amg/hierarchy.py), the RequestBatcher
(batch/queue.py), the fallback engine (resilience/policy.py), jit
retraces per solver entry point (solvers/base.py, batch/core.py,
distributed/solver.py), and device-memory watermarks per phase
(memory_info sampled from solvers/base.py).
"""
from __future__ import annotations

import bisect
import os
import re
import threading
from typing import Any, Dict, Optional, Tuple, Union

_lock = threading.Lock()
# fleet identity: a replica/shard label stamped on EVERY OpenMetrics
# sample so multi-replica scrapes don't collide (the ROADMAP-3a fleet
# prerequisite). Sources, later wins: AMGX_REPLICA_ID env (read once,
# lazily) then the serving_replica_id config knob (SolveService
# construction calls set_replica_label)
_replica: Optional[str] = None
_replica_env_checked = False
_counters: Dict[str, int] = {}
_gauges: Dict[str, float] = {}
# (name, sorted-label-items tuple) -> {"counts": [..], "sum": ., "count": .}
_hists: Dict[Tuple[str, tuple], dict] = {}

# name -> doc; the declaration IS the catalog
COUNTERS: Dict[str, str] = {}
GAUGES: Dict[str, str] = {}
HISTOGRAMS: Dict[str, str] = {}
HISTOGRAM_EDGES: Dict[str, tuple] = {}


def declare_counter(name: str, doc: str):
    COUNTERS[name] = doc


def declare_gauge(name: str, doc: str):
    GAUGES[name] = doc


def declare_histogram(name: str, doc: str, edges):
    """Declare a histogram with FIXED bucket upper bounds (`le`
    semantics: bucket i counts samples <= edges[i]; one implicit
    overflow bucket past the last edge). Edges are part of the
    declaration — every process observes into the same buckets, so
    snapshots merge across runs."""
    edges = tuple(float(e) for e in edges)
    if not edges or list(edges) != sorted(set(edges)):
        raise ValueError(
            f"histogram {name!r}: edges must be strictly increasing, "
            f"got {edges}")
    HISTOGRAMS[name] = doc
    HISTOGRAM_EDGES[name] = edges


def _unknown(name: str, catalog: Dict[str, str], kind: str):
    from ..errors import did_you_mean
    raise KeyError(f"undeclared telemetry {kind} {name!r}"
                   f"{did_you_mean(name, catalog)}")


def inc(name: str, n: int = 1):
    """Increment a declared counter."""
    if name not in COUNTERS:
        _unknown(name, COUNTERS, "counter")
    with _lock:
        _counters[name] = _counters.get(name, 0) + int(n)


def set_gauge(name: str, value: Union[int, float]):
    """Set a declared gauge to its latest value."""
    if name not in GAUGES:
        _unknown(name, GAUGES, "gauge")
    with _lock:
        _gauges[name] = value


def max_gauge(name: str, value: Union[int, float]):
    """Fold a sample into a declared high-water-mark gauge."""
    if name not in GAUGES:
        _unknown(name, GAUGES, "gauge")
    with _lock:
        _gauges[name] = max(_gauges.get(name, value), value)


def _label_key(labels: Optional[Dict[str, str]]) -> tuple:
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


def observe(name: str, value: Union[int, float],
            labels: Optional[Dict[str, str]] = None):
    """Fold one sample into a declared histogram. `labels` splits the
    series (e.g. {"tenant": ...} for per-tenant latency); each label
    set keeps its own buckets, and quantile()/snapshot() can aggregate
    across them."""
    if name not in HISTOGRAMS:
        _unknown(name, HISTOGRAMS, "histogram")
    edges = HISTOGRAM_EDGES[name]
    v = float(value)
    idx = bisect.bisect_left(edges, v)    # first edge >= v (le bucket)
    key = (name, _label_key(labels))
    with _lock:
        h = _hists.get(key)
        if h is None:
            h = _hists[key] = {"counts": [0] * (len(edges) + 1),
                               "sum": 0.0, "count": 0}
        h["counts"][idx] += 1
        h["sum"] += v
        h["count"] += 1


def _merged_hist(name: str):
    """Aggregate one histogram's label variants (caller holds _lock)."""
    edges = HISTOGRAM_EDGES[name]
    counts = [0] * (len(edges) + 1)
    total, n = 0.0, 0
    for (nm, _lk), h in _hists.items():
        if nm != name:
            continue
        for i, c in enumerate(h["counts"]):
            counts[i] += c
        total += h["sum"]
        n += h["count"]
    return counts, total, n


def _quantile_from_counts(edges, counts, q: float) -> Optional[float]:
    """Bucket-interpolated quantile: find the bucket holding the q-th
    sample, linearly interpolate within its [lower, upper] edge span
    (lower = 0 for the first bucket; the overflow bucket reports the
    last edge — the estimate saturates at the declared range)."""
    n = sum(counts)
    if n == 0:
        return None
    target = q * n
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            if i >= len(edges):
                return float(edges[-1])
            lo = 0.0 if i == 0 else edges[i - 1]
            hi = edges[i]
            frac = (target - (cum - c)) / max(c, 1)
            return float(lo + (hi - lo) * frac)
    return float(edges[-1])


def quantile(name: str, q: float,
             labels: Optional[Dict[str, str]] = None
             ) -> Optional[float]:
    """Estimated q-quantile of a declared histogram (None = no
    samples). labels=None aggregates every label variant; a labels dict
    reads that one series."""
    if name not in HISTOGRAMS:
        _unknown(name, HISTOGRAMS, "histogram")
    edges = HISTOGRAM_EDGES[name]
    with _lock:
        if labels is None:
            counts, _tot, _n = _merged_hist(name)
        else:
            h = _hists.get((name, _label_key(labels)))
            counts = h["counts"] if h else [0] * (len(edges) + 1)
    return _quantile_from_counts(edges, counts, q)


def get(name: str) -> Union[int, float, dict]:
    """Current value (0 for a declared counter/gauge never touched; a
    histogram returns its merged-across-labels snapshot entry)."""
    if name in COUNTERS:
        with _lock:
            return _counters.get(name, 0)
    if name in GAUGES:
        with _lock:
            return _gauges.get(name, 0)
    if name in HISTOGRAMS:
        edges = HISTOGRAM_EDGES[name]
        with _lock:
            counts, total, n = _merged_hist(name)
        return _hist_snapshot_entry(name, edges, counts, total, n)
    _unknown(name, {**COUNTERS, **GAUGES, **HISTOGRAMS}, "metric")


def _hist_snapshot_entry(name, edges, counts, total, n):
    return {
        "count": n,
        "sum": total,
        "edges": list(edges),
        "counts": list(counts),
        "p50": _quantile_from_counts(edges, counts, 0.50),
        "p90": _quantile_from_counts(edges, counts, 0.90),
        "p99": _quantile_from_counts(edges, counts, 0.99),
    }


def snapshot() -> Dict[str, Union[int, float, dict]]:
    """JSON-ready dump: every declared counter (zeros included, so the
    key set is stable run to run), every gauge that has a sample, and
    every declared histogram (aggregated across labels under its bare
    name — empty ones included — plus one `name{k="v",...}` entry per
    touched label set, each with counts/sum/edges and estimated
    p50/p90/p99)."""
    with _lock:
        out: Dict[str, Union[int, float, dict]] = {
            name: _counters.get(name, 0) for name in COUNTERS}
        out.update(_gauges)
        for name in HISTOGRAMS:
            edges = HISTOGRAM_EDGES[name]
            counts, total, n = _merged_hist(name)
            out[name] = _hist_snapshot_entry(name, edges, counts,
                                             total, n)
        for (name, lk), h in _hists.items():
            if not lk:
                continue     # the unlabeled series IS the merged entry
            disp = name + "{" + ",".join(
                f'{k}="{_om_label_escape(v)}"' for k, v in lk) + "}"
            out[disp] = _hist_snapshot_entry(
                name, HISTOGRAM_EDGES[name], h["counts"], h["sum"],
                h["count"])
        return out


def quantile_where(name: str, q: float,
                   labels: Dict[str, str]) -> Optional[float]:
    """Estimated q-quantile aggregated across every label variant of
    `name` whose label set CONTAINS the given pairs (subset match,
    vs. quantile()'s exact match). This is the fleet-level read:
    ``quantile_where("serving.solve_latency_s", 0.99, {"tenant":
    "a"})`` folds tenant `a`'s series across every replica label into
    one distribution. None = no matching samples."""
    if name not in HISTOGRAMS:
        _unknown(name, HISTOGRAMS, "histogram")
    want = {(str(k), str(v)) for k, v in (labels or {}).items()}
    edges = HISTOGRAM_EDGES[name]
    counts = [0] * (len(edges) + 1)
    with _lock:
        for (nm, lk), h in _hists.items():
            if nm != name or not want.issubset(set(lk)):
                continue
            for i, c in enumerate(h["counts"]):
                counts[i] += c
    return _quantile_from_counts(edges, counts, q)


def reset():
    """Zero every counter and drop every gauge/histogram sample
    (declarations stay — a reset registry still documents its
    catalog)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()


# ---------------------------------------------------------------------------
# fleet snapshot merging (serving/fleet.py + cross-process aggregation)
# ---------------------------------------------------------------------------

_ENTRY_KEY_RE = re.compile(r'^([^{]+)\{(.*)\}$')
_LABEL_PAIR_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _label_unescape(s: str) -> str:
    return re.sub(r'\\(.)',
                  lambda m: {"n": "\n"}.get(m.group(1), m.group(1)), s)


def _parse_entry_key(key: str) -> Tuple[str, tuple]:
    """Snapshot-entry key -> (name, ((k, v), ...)): the inverse of the
    `name{k="v",...}` rendering snapshot() uses for labeled histogram
    series; a bare name parses to (key, ())."""
    m = _ENTRY_KEY_RE.match(key)
    if not m:
        return key, ()
    pairs = tuple((k, _label_unescape(v))
                  for k, v in _LABEL_PAIR_RE.findall(m.group(2)))
    return m.group(1), pairs


def merge_snapshots(snaps: Dict[str, dict]
                    ) -> Dict[str, Union[int, float, dict]]:
    """Fleet-wide aggregate of per-replica snapshot() dumps, keyed by
    replica id: ``merge_snapshots({"r0": snap0, "r1": snap1})``.

    Scalars (counters and gauges) SUM — both are live totals that add
    across a fleet (completed requests, queue depths, cache bytes).
    Histogram entries merge bucket-wise; edges are part of the
    declaration, so a mismatch across snapshots raises instead of
    producing a silently wrong distribution, and p50/p90/p99 are
    recomputed from the merged counts (never averaged). A LABELED
    entry missing a ``replica`` label gains one from its snapshot's
    key, so two replicas' same-named per-tenant series never collide
    in the merge — the in-process analog of the `serving_replica_id`
    scrape label. For every histogram with labeled entries but no
    bare aggregate in the inputs (per-replica filtered views), the
    fleet-wide bare aggregate is synthesized from the labeled
    series."""
    scalars: Dict[str, Union[int, float]] = {}
    # (name, sorted label pairs) -> [counts, sum, count, edges]
    hists: Dict[Tuple[str, tuple], list] = {}
    bare_seen = set()

    def _fold(hk, val):
        cur = hists.get(hk)
        edges = tuple(val["edges"])
        counts = val["counts"]
        if cur is None:
            hists[hk] = [list(counts), float(val["sum"]),
                         int(val["count"]), edges]
            return
        if edges != cur[3] or len(counts) != len(cur[0]):
            raise ValueError(
                f"merge_snapshots: histogram {hk[0]!r} bucket edges "
                f"differ across snapshots — edges are part of the "
                f"declaration and must match to merge")
        for i, c in enumerate(counts):
            cur[0][i] += c
        cur[1] += float(val["sum"])
        cur[2] += int(val["count"])

    for rid, snap in snaps.items():
        for key, val in (snap or {}).items():
            if isinstance(val, dict) and "counts" in val \
                    and "edges" in val:
                name, pairs = _parse_entry_key(key)
                if not pairs:
                    bare_seen.add(name)
                elif not any(k == "replica" for k, _v in pairs):
                    pairs = pairs + (("replica", str(rid)),)
                _fold((name, tuple(sorted(pairs))), val)
            elif isinstance(val, (int, float)) \
                    and not isinstance(val, bool):
                scalars[key] = scalars.get(key, 0) + val
    # synthesize the fleet-wide bare aggregate where the inputs only
    # carried labeled series (per-replica views)
    for (name, pairs), (counts, total, n, edges) in list(hists.items()):
        if not pairs or name in bare_seen:
            continue
        _fold((name, ()), {"counts": counts, "sum": total,
                           "count": n, "edges": edges})
    out: Dict[str, Union[int, float, dict]] = dict(scalars)
    for (name, pairs), (counts, total, n, edges) in sorted(
            hists.items()):
        disp = name if not pairs else name + "{" + ",".join(
            f'{k}="{_om_label_escape(v)}"' for k, v in pairs) + "}"
        out[disp] = _hist_snapshot_entry(name, edges, counts, total, n)
    return out


# ---------------------------------------------------------------------------
# OpenMetrics text exposition
# ---------------------------------------------------------------------------


def _om_name(name: str) -> str:
    """Registry name -> OpenMetrics metric name: dots become
    underscores under an `amgx_` namespace ('serving.cache.hit' ->
    'amgx_serving_cache_hit')."""
    return "amgx_" + name.replace(".", "_").replace("-", "_")


def _om_escape(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _om_label_escape(s: str) -> str:
    """Label-value escaping: the OpenMetrics grammar additionally
    escapes double quotes inside label values — a caller-provided
    tenant id containing a quote must not break the whole scrape."""
    return _om_escape(s).replace('"', r'\"')


def _om_num(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    v = float(v)
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v)


def set_replica_label(replica: Optional[str]):
    """Set (or clear, with None/'') the replica label every
    OpenMetrics sample carries as `replica="..."`. Process-wide, like
    the registry itself: one serving replica = one process."""
    global _replica, _replica_env_checked
    _replica_env_checked = True
    _replica = str(replica) if replica else None


def replica_label() -> Optional[str]:
    """The active replica label (AMGX_REPLICA_ID env read lazily once;
    an explicit set_replica_label overrides either way)."""
    global _replica, _replica_env_checked
    if not _replica_env_checked:
        _replica_env_checked = True
        env = os.environ.get("AMGX_REPLICA_ID", "").strip()
        if env:
            _replica = env
    return _replica


def _om_labels(items) -> str:
    rep = replica_label()
    if rep is not None and not any(k == "replica" for k, _v in items):
        items = (("replica", rep),) + tuple(items)
    if not items:
        return ""
    return "{" + ",".join(
        f'{k}="{_om_label_escape(v)}"' for k, v in items) + "}"


def to_openmetrics() -> str:
    """The whole registry as an OpenMetrics text exposition (the
    /metrics scrape payload): HELP/TYPE metadata per family, `_total`
    samples for counters, plain samples for gauges, cumulative
    `_bucket{le=...}` + `_sum`/`_count` per histogram label set, and
    the mandatory `# EOF` terminator. Declared-but-untouched counters
    and histograms expose zeros (stable scrape shape); unsampled
    gauges are omitted (a gauge has no meaningful zero). When a
    replica label is configured (`AMGX_REPLICA_ID` env or the
    serving_replica_id knob via set_replica_label), EVERY sample
    carries `replica="..."` so multi-replica scrapes never collide."""
    lines = []
    with _lock:
        for name in sorted(COUNTERS):
            om = _om_name(name)
            lines.append(f"# HELP {om} {_om_escape(COUNTERS[name])}")
            lines.append(f"# TYPE {om} counter")
            lines.append(f"{om}_total{_om_labels(())} "
                         f"{_om_num(_counters.get(name, 0))}")
        for name in sorted(GAUGES):
            if name not in _gauges:
                continue
            om = _om_name(name)
            lines.append(f"# HELP {om} {_om_escape(GAUGES[name])}")
            lines.append(f"# TYPE {om} gauge")
            lines.append(f"{om}{_om_labels(())} {_om_num(_gauges[name])}")
        for name in sorted(HISTOGRAMS):
            om = _om_name(name)
            edges = HISTOGRAM_EDGES[name]
            lines.append(f"# HELP {om} {_om_escape(HISTOGRAMS[name])}")
            lines.append(f"# TYPE {om} histogram")
            series = sorted(
                (lk, h) for (nm, lk), h in _hists.items() if nm == name)
            if not series:
                series = [((), {"counts": [0] * (len(edges) + 1),
                                "sum": 0.0, "count": 0})]
            for lk, h in series:
                cum = 0
                for i, edge in enumerate(edges):
                    cum += h["counts"][i]
                    lab = _om_labels(lk + (("le", _om_num(edge)),))
                    lines.append(f"{om}_bucket{lab} {cum}")
                lab = _om_labels(lk + (("le", "+Inf"),))
                lines.append(f"{om}_bucket{lab} {h['count']}")
                base = _om_labels(lk)
                lines.append(f"{om}_sum{base} {_om_num(h['sum'])}")
                lines.append(f"{om}_count{base} {h['count']}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# the catalog
# ---------------------------------------------------------------------------

# AMG setup routing (amg/hierarchy.py): how coefficient updates reach
# the hierarchy — the 17.4s-vs-0.43s difference between a full setup
# and a value-resetup is THE serving-layer routing decision to watch
declare_counter("amg.setup.full",
                "full hierarchy builds (structure re-coarsened)")
declare_counter("amg.resetup.value",
                "fused value-only resetups (structure + traces kept)")
declare_counter("amg.resetup.structure",
                "structure-reuse resetups (kept levels re-valued, "
                "deeper levels rebuilt)")
declare_counter("amg.setup.restored",
                "setups served from a persisted structure snapshot "
                "(serving/hstore.py: load + structure-reuse rebuild — "
                "the crash-recovery path that replaces a full setup)")
declare_counter("amg.selector.device_sweep",
                "RS/HMIS first passes taken by the device-parallel "
                "independent-set sweep instead of the host-serial "
                "bucket queue (selector_device_sweep routing)")

# fused-kernel routing (ops/smooth.py): a level that CARRIES a fused
# payload but falls off the fused path is a silent 2x HBM regression —
# the decline is counted at trace time and SolveReport's kernel-
# activity table records the per-level routing + effective dtype
declare_counter("fusion.declined_dtype",
                "fused-kernel dispatches declined because the operand "
                "dtype is off the kernel whitelist (ops/pallas_spmv.py "
                "SMOOTH_DTYPES) — the config fell back to the unfused "
                "composition; see SolveReport levels[].fused_routing")

# Krylov shell fusion routing (ops/spmv.spmv_pdot / spmv_ddot,
# ops/blas.cg_update): trace-time counts of which route the shell's
# fused call sites actually took — a krylov_fusion=1 solve whose
# operator silently falls off the kernels (non-DIA layout, blocks,
# f64, VMEM overrun) pays 2-3x the n-vector HBM passes per iteration
declare_counter("krylov.fused_dispatch",
                "Krylov shell dispatches routed to the single-pass "
                "Pallas kernels (SpMV+dot, CG update) at trace time")
declare_counter("krylov.fused_declined",
                "Krylov shell dispatches that fell back to the "
                "unfused-expression XLA compose (non-DIA/block "
                "operator, off-whitelist dtype, or VMEM gate) — same "
                "results, more HBM passes per iteration")

# GEO Galerkin CSR-structure device cache (amg/aggregation/galerkin.py):
# a miss at 256^3 re-uploads ~1 GB of structure arrays per warm setup
declare_counter("amg.geo_struct_cache.hit",
                "GEO coarse CSR-structure device-cache hits")
declare_counter("amg.geo_struct_cache.miss",
                "GEO coarse CSR-structure device-cache misses "
                "(host build + device upload paid)")

# plan-split Galerkin RAP (ops/spgemm.py RapPlan): a warm setup or
# value resetup of a known pattern must HIT (zero symbolic work, one
# fused value kernel per level); builds are the once-per-pattern
# structure phase
declare_counter("amg.spgemm.plan_build",
                "RAP structure-phase plan builds (once per sparsity "
                "pattern: expansion gathers + coalesce order + output "
                "CSR, host numpy)")
declare_counter("amg.spgemm.plan_hit",
                "RAP plan-cache hits (warm setup / resetup of a known "
                "pattern: value phase only, zero symbolic work)")

# RequestBatcher (batch/queue.py)
declare_counter("batch.requests", "solve requests submitted")
declare_counter("batch.dispatches", "batched dispatches issued")
declare_counter("batch.bucket_evictions",
                "pattern buckets evicted from the RequestBatcher's "
                "bounded solver store (count or bytes budget exceeded)")
declare_counter("batch.padded_systems",
                "pad-waste systems dispatched (ladder rung minus real "
                "requests, summed over dispatches)")
declare_gauge("batch.bucket_occupancy",
              "real/padded ratio of the last dispatch (1.0 = no waste)")
declare_gauge("batch.live_buckets",
              "live pattern buckets (each holds a hierarchy + compiled "
              "programs)")

# resilience fallback engine (resilience/policy.py)
declare_counter("resilience.fallback_attempts",
                "total fallback-chain steps executed")
declare_counter("resilience.fallback.retry", "plain retry actions run")
declare_counter("resilience.fallback.rescale_retry",
                "rescale_retry actions run")
declare_counter("resilience.fallback.switch_solver",
                "switch_solver actions run")
declare_counter("resilience.fallback.escalate_sweeps",
                "escalate_sweeps actions run")
declare_counter("resilience.config_fallback",
                "known-fault configurations rerouted at validation "
                "time (e.g. MULTICOLOR_DILU at >96^3 rows on a TPU "
                "-> the documented JACOBI_L1 fallback) instead of "
                "failing at solve time")

# jit retraces per solver entry point: a retrace in steady-state serving
# is a latency cliff (first-request trace cost paid again)
declare_counter("solver.retrace.solve",
                "single-solve jit cache misses (Solver.solve)")
declare_counter("solver.retrace.solve_batched",
                "batched-solve jit cache misses "
                "(BatchedSolver.solve_many)")
declare_counter("solver.retrace.distributed",
                "distributed-solve shard_map rebuilds "
                "(DistributedSolver.solve)")

# serving subsystem (amgx_tpu/serving/): the production solve service —
# continuous batching, hierarchy cache routing, AOT warm paths and
# per-tenant deadlines all report here
declare_counter("serving.requests",
                "solve requests submitted to the service")
declare_counter("serving.completed",
                "requests completed (any terminal status)")
declare_counter("serving.rejected",
                "requests rejected without solving (admission control "
                "queue bound, or reject-on-deadline action)")
declare_counter("serving.deadline_miss",
                "requests whose deadline expired before convergence "
                "(completed with DEADLINE_EXCEEDED, queued or in-flight)")
declare_counter("serving.cache.hit",
                "hierarchy-cache hits: request fingerprint matched a "
                "live bucket, so admission routes through value-resetup "
                "instead of a full AMG setup")
declare_counter("serving.cache.miss",
                "hierarchy-cache misses (full setup paid to build a "
                "new bucket)")
declare_counter("serving.cache.evictions",
                "idle buckets evicted to fit the cache byte budget")
declare_counter("serving.retrace",
                "serving-engine python traces (init/step/finish); zero "
                "in steady state and zero from the first request when "
                "the AOT store warmed the bucket")
declare_counter("serving.aot.export",
                "bucket executables exported + persisted via jax.export")
declare_counter("serving.aot.load",
                "bucket executables loaded from the AOT store (trace "
                "latency skipped)")
declare_counter("serving.aot.error",
                "AOT export/load failures degraded to plain tracing")
declare_counter("serving.deadline_action.partial",
                "expired in-flight requests completed with their "
                "current iterate")
declare_counter("serving.deadline_action.reject",
                "expired requests completed with the zero/initial "
                "iterate (reject action)")
# serving latency distributions (serving/service.py): fixed log-spaced
# bucket edges covering sub-ms admission waits through multi-minute
# cold-setup outliers; labeled by tenant so per-tenant p50/p99 are live
# service state (service.stats(), the OpenMetrics scrape) rather than
# bench-only aggregates
_LATENCY_EDGES_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                    120.0)
declare_histogram("serving.solve_latency_s",
                  "submit-to-complete latency per request (seconds), "
                  "labeled tenant=<id>; every terminal status counts "
                  "(a deadline miss is latency the caller saw too)",
                  _LATENCY_EDGES_S)
declare_histogram("serving.queue_wait_s",
                  "submit-to-slot-admission wait per request "
                  "(seconds), labeled tenant=<id>; the queueing half "
                  "of solve latency — what admission control and "
                  "bucket sizing tune",
                  _LATENCY_EDGES_S)
declare_gauge("serving.queue_depth",
              "requests waiting for a bucket slot")
declare_gauge("serving.inflight",
              "requests currently occupying bucket slots")
declare_gauge("serving.live_buckets",
              "live serving buckets (each: hierarchy + engine traces)")
declare_gauge("serving.cache.bytes",
              "estimated device bytes held by live serving buckets")

# serving fault tolerance (serving/{journal,hstore}.py + the
# service-level recovery/shed machinery in serving/service.py)
declare_counter("serving.recovery.checkpoints",
                "in-flight solve states journaled at cycle boundaries "
                "(serving_checkpoint_cycles cadence)")
declare_counter("serving.recovery.replayed",
                "journaled requests re-admitted by a restarted service")
declare_counter("serving.recovery.resumed",
                "replayed requests that resumed from a checkpointed "
                "iterate instead of iteration 0")
declare_counter("serving.recovery.restart_fresh",
                "replayed requests whose checkpoint was unusable "
                "(missing/corrupt/layout drift) and restarted clean")
declare_counter("serving.recovery.journal_corrupt",
                "journal records dropped as corrupt during recovery "
                "(torn writes; the rest of the journal still replays)")
declare_counter("serving.recovery.quarantined",
                "buckets quarantined by the supervisor (device-step "
                "exception or flatlined progress heartbeat)")
declare_counter("serving.recovery.salvaged",
                "slots of a quarantined bucket finalized with their "
                "current terminal iterate")
declare_counter("serving.recovery.requeued",
                "slots of a quarantined bucket requeued for a rebuilt "
                "bucket (resuming from their live/checkpointed state)")
declare_counter("serving.recovery.build_retries",
                "bucket builds retried under the serving_fault_policy "
                "backoff chain")
declare_counter("serving.recovery.hstore_save",
                "hierarchy structure snapshots persisted")
declare_counter("serving.recovery.hstore_load",
                "hierarchy structure snapshots restored (the restart "
                "setup became a structure-reuse rebuild)")
declare_counter("serving.recovery.hstore_skip",
                "hierarchy snapshots skipped (a level class without "
                "persistence support)")
declare_counter("serving.recovery.hstore_error",
                "hierarchy store save/load failures degraded to a "
                "full setup")
declare_counter("serving.dedupe",
                "submits deduplicated against a live ticket or the "
                "journal via the client request key")
declare_counter("serving.shed.overload",
                "requests shed OVERLOADED at the admission queue bound")
declare_counter("serving.shed.deadline",
                "requests shed OVERLOADED because the live latency "
                "estimate said the deadline was unmeetable")
declare_counter("serving.shed.quota",
                "requests shed OVERLOADED by the per-tenant fairness "
                "quota")
declare_histogram("serving.exec_s",
                  "slot-admission-to-complete execution time per "
                  "request (seconds), labeled tenant=<id>; the "
                  "in-bucket half of solve latency — what the shed "
                  "policy's deadline-feasibility estimate reads",
                  _LATENCY_EDGES_S)
declare_gauge("serving.bucket_width",
              "slot width of the most recently built serving bucket — "
              "the mixed-width ladder's live choice "
              "(serving_bucket_ladder; fixed-width services report "
              "serving_bucket_slots)")

# fleet router (serving/fleet.py): fingerprint-affine routing over N
# SolveService replicas — every routing decision lands in exactly one
# of the three route classes
declare_counter("fleet.route.warm",
                "requests routed to their fingerprint's home replica "
                "(rendezvous-hash affinity): warm hierarchy cache, "
                "hstore and AOT paths")
declare_counter("fleet.route.cold",
                "first-seen fingerprints placed on the least-loaded "
                "replica (live queue depth x recent exec estimate), "
                "becoming its home")
declare_counter("fleet.route.spill",
                "requests diverted off an overloaded, "
                "quarantine-looping or deadline-infeasible home "
                "replica to the next rendezvous candidate (each spill "
                "writes a fleet.handoff flight-recorder note)")
declare_counter("fleet.shed.infeasible",
                "submits whose deadline the FLEET-WIDE feasibility "
                "aggregate (per-replica estimates + merged per-tenant "
                "latency) judged unmeetable on every replica — routed "
                "home anyway so the replica's shed policy completes "
                "them honestly OVERLOADED")
declare_gauge("fleet.replicas",
              "replicas fronted by the live FleetRouter")

# fleet health + failover (serving/health.py + fleet.py): the
# breaker/liveness layer's literal transition counters — one per
# detector/transition so a scrape alone reconstructs the incident
declare_counter("fleet.health.suspect",
                "busy replicas whose scheduler-cycle counter first "
                "flatlined across a heartbeat window (the wedge "
                "detector's first strike)")
declare_counter("fleet.health.wedged",
                "REPLICA_WEDGED events: a busy replica's cycle "
                "counter flatlined fleet_suspect_checks consecutive "
                "heartbeat windows")
declare_counter("fleet.health.slow",
                "REPLICA_SLOW events: per-cycle wall between health "
                "checks exceeded fleet_slow_cycle_s")
declare_counter("fleet.health.dead",
                "REPLICA_DEAD detections: a captured scheduler "
                "exception, or a started thread no longer alive "
                "without stop()")
declare_counter("fleet.health.down",
                "replicas marked DOWN (failover ran; only "
                "restore_replica resets)")
declare_counter("fleet.health.breaker_open",
                "breaker OPEN transitions (probe_backoff policy "
                "action: no traffic until the bounded backoff "
                "elapses)")
declare_counter("fleet.health.breaker_half_open",
                "breaker HALF_OPEN transitions (backoff elapsed: one "
                "trial fingerprint may probe)")
declare_counter("fleet.health.breaker_closed",
                "breakers closed by a successful probe (a completion "
                "since the probe began)")
declare_counter("fleet.health.probe_trials",
                "HALF_OPEN probe admissions (exactly one fingerprint "
                "per probe window)")
declare_counter("fleet.health.rehomed",
                "fingerprint placements moved off a DOWN replica "
                "along rendezvous order during failover")
declare_counter("fleet.health.requeued",
                "tickets (queued + in-flight) moved off a down or "
                "draining replica into survivor queues")
declare_counter("fleet.health.adopted",
                "pending journal records a survivor replayed from a "
                "dead replica's adopted journal (cross-replica "
                "recover)")
declare_counter("fleet.health.drains",
                "administrative drain_replica calls (rolling "
                "restarts)")
declare_counter("fleet.health.restores",
                "restore_replica calls re-entering a replica into "
                "the rendezvous")
declare_gauge("fleet.health.available",
              "replicas currently able to take traffic (not down, "
              "not draining, breaker not OPEN)")

# online config autotuner (serving/autotune.py, autotune=1): the
# watch -> generate -> shadow -> promote/demote lifecycle, each
# transition counted where it happens — with autotune=0 every series
# below stays at zero (the bitwise-inert contract's observable half)
declare_counter("autotune.hot",
                "fingerprints crossing both hot thresholds "
                "(autotune_hot_requests AND autotune_hot_exec_share) "
                "— searches opened")
declare_counter("autotune.candidates",
                "candidate configs generated from shadow-baseline "
                "diagnostics (suggest_config_deltas output, summed "
                "over searches)")
declare_counter("autotune.shadow.runs",
                "completed shadow solves (baseline probes + "
                "candidates), run only on idle capacity")
declare_counter("autotune.shadow.errors",
                "shadow solves that raised (absorbed: counted, backed "
                "off, never a failed ticket)")
declare_counter("autotune.promotions",
                "candidate configs promoted to a fingerprint's "
                "serving overlay (won iterations AND wall past the "
                "autotune_min_improvement gate)")
declare_counter("autotune.demotions",
                "promoted overlays dropped by the live regression "
                "watch (post-promotion exec median regressed past "
                "autotune_demote_factor)")
declare_counter("autotune.overlay.applied",
                "bucket builds that applied a tuned-config overlay "
                "(promoted or restored fingerprints)")
declare_counter("autotune.overlay.restored",
                "tuned-config overlays restored from the hstore's "
                "persisted record (restart durability: resolved "
                "before the fingerprint's first build)")
declare_counter("autotune.handoffs",
                "promoted overlays handed to a survivor replica "
                "during fleet drain/failover (adopted live + "
                "persisted in the adopter's hstore)")
declare_gauge("autotune.tuned_fingerprints",
              "fingerprints currently serving a promoted tuned-config "
              "overlay")
declare_histogram("autotune.shadow_wall_s",
                  "wall seconds per shadow solve (setup + cold + "
                  "measured warm pass — the idle-capacity cost of "
                  "the search)", edges=_LATENCY_EDGES_S)

# distributed comms/shard telemetry (distributed/comms.py records at
# TRACE time — collectives are emitted by the traced program, so the
# honest countable event is the traced exchange SITE; bytes are the
# MODELED per-direction window sizes of that site, exact by
# construction from the partition metadata, not measured wire traffic)
declare_counter("dist.exchange.calls",
                "halo/edge exchange sites traced (all modes; one per "
                "exchange site per traced program, NOT per executed "
                "iteration)")
declare_counter("dist.exchange.ring",
                "ring-mode halo exchange sites traced (two ppermutes "
                "per site)")
declare_counter("dist.exchange.a2a",
                "all-to-all-mode halo exchange sites traced")
declare_counter("dist.exchange.gather",
                "all-gather-mode halo exchange sites traced (the "
                "dense-boundary fallback)")
declare_counter("dist.exchange.edge_fused",
                "packed edge-window exchange sites traced by the "
                "halo-folded fused path (distributed/fused.py: one "
                "collective per fused smoother call)")
declare_counter("dist.comms.bytes_fwd",
                "modeled bytes shipped FORWARD (toward rank+1) per "
                "traced exchange site, summed over the whole mesh "
                "(per-hop window elements x itemsize x sending ranks)")
declare_counter("dist.comms.bytes_bwd",
                "modeled bytes shipped BACKWARD (toward rank-1) per "
                "traced exchange site, summed over the whole mesh")
declare_gauge("dist.shard.rows_imbalance",
              "per-shard row imbalance of the live partition "
              "(max rows over mean rows; 1.0 = perfectly balanced)")
declare_gauge("dist.shard.nnz_imbalance",
              "per-shard nonzero imbalance of the live partition "
              "(max nnz over mean nnz) — the load-balance number the "
              "per-chip-throughput gate attribution needs")

# flight recorder (telemetry/flightrec.py)
declare_counter("flightrec.events",
                "flight-recorder events recorded (state transitions: "
                "builds, quarantines, sheds, fallback hops, resetup "
                "routing, chaos injections)")
declare_counter("flightrec.dropped",
                "corrupt flight-recorder lines dropped at read "
                "(torn-write tolerance; the postmortem never wedges)")

# device-memory watermarks per phase (memory_info allocator statistics
# sampled at phase boundaries; the backend's own peak_bytes_in_use is
# preferred so transient in-phase maxima — Galerkin temporaries freed
# before the boundary — are captured; zero on backends reporting none)
declare_gauge("memory.setup_peak_bytes",
              "device-allocator high-water mark (bytes) sampled at "
              "setup/resetup completion")
declare_gauge("memory.solve_peak_bytes",
              "device-allocator high-water mark (bytes) sampled at "
              "solve completion")
