"""Process-wide counter/gauge registry.

The reference exposes its runtime behavior through scattered printf
tables; a serving deployment needs the numbers a scrape endpoint or a
periodic dump can read: cache hit rates, retrace counts, batcher
occupancy, memory watermarks. This module is that registry — one flat,
thread-safe, process-wide namespace of declared metrics.

Design rules:

- every metric is DECLARED up front (name + kind + doc) so the registry
  doubles as the documentation of what the library measures; an
  undeclared name raises with a did-you-mean suggestion instead of
  silently forking a typo'd time series;
- counters are monotonic within a process (`inc`); gauges are
  last-value (`set_gauge`) or high-water (`max_gauge`);
- recording is a dict update under one lock — cheap enough to stay
  unconditional (the `telemetry` config knob gates report construction
  and span fencing, not counter arithmetic);
- `snapshot()` returns a plain dict (JSON-ready) of every metric that
  has been touched, plus zeros for declared-but-untouched counters so a
  dump always has a stable key set.

Instrumented sites (see the declarations below for the full catalog):
the GEO Galerkin structure-cache (amg/aggregation/galerkin.py), the
setup/resetup routing (amg/hierarchy.py), the RequestBatcher
(batch/queue.py), the fallback engine (resilience/policy.py), jit
retraces per solver entry point (solvers/base.py, batch/core.py,
distributed/solver.py), and device-memory watermarks per phase
(memory_info sampled from solvers/base.py).
"""
from __future__ import annotations

import threading
from typing import Dict, Union

_lock = threading.Lock()
_counters: Dict[str, int] = {}
_gauges: Dict[str, float] = {}

# name -> doc; the declaration IS the catalog
COUNTERS: Dict[str, str] = {}
GAUGES: Dict[str, str] = {}


def declare_counter(name: str, doc: str):
    COUNTERS[name] = doc


def declare_gauge(name: str, doc: str):
    GAUGES[name] = doc


def _unknown(name: str, catalog: Dict[str, str], kind: str):
    from ..errors import did_you_mean
    raise KeyError(f"undeclared telemetry {kind} {name!r}"
                   f"{did_you_mean(name, catalog)}")


def inc(name: str, n: int = 1):
    """Increment a declared counter."""
    if name not in COUNTERS:
        _unknown(name, COUNTERS, "counter")
    with _lock:
        _counters[name] = _counters.get(name, 0) + int(n)


def set_gauge(name: str, value: Union[int, float]):
    """Set a declared gauge to its latest value."""
    if name not in GAUGES:
        _unknown(name, GAUGES, "gauge")
    with _lock:
        _gauges[name] = value


def max_gauge(name: str, value: Union[int, float]):
    """Fold a sample into a declared high-water-mark gauge."""
    if name not in GAUGES:
        _unknown(name, GAUGES, "gauge")
    with _lock:
        _gauges[name] = max(_gauges.get(name, value), value)


def get(name: str) -> Union[int, float]:
    """Current value (0 for a declared counter/gauge never touched)."""
    if name in COUNTERS:
        with _lock:
            return _counters.get(name, 0)
    if name in GAUGES:
        with _lock:
            return _gauges.get(name, 0)
    _unknown(name, {**COUNTERS, **GAUGES}, "metric")


def snapshot() -> Dict[str, Union[int, float]]:
    """JSON-ready dump: every declared counter (zeros included, so the
    key set is stable run to run) plus every gauge that has a sample."""
    with _lock:
        out: Dict[str, Union[int, float]] = {
            name: _counters.get(name, 0) for name in COUNTERS}
        out.update(_gauges)
        return out


def reset():
    """Zero every counter and drop every gauge sample (declarations
    stay — a reset registry still documents its catalog)."""
    with _lock:
        _counters.clear()
        _gauges.clear()


# ---------------------------------------------------------------------------
# the catalog
# ---------------------------------------------------------------------------

# AMG setup routing (amg/hierarchy.py): how coefficient updates reach
# the hierarchy — the 17.4s-vs-0.43s difference between a full setup
# and a value-resetup is THE serving-layer routing decision to watch
declare_counter("amg.setup.full",
                "full hierarchy builds (structure re-coarsened)")
declare_counter("amg.resetup.value",
                "fused value-only resetups (structure + traces kept)")
declare_counter("amg.resetup.structure",
                "structure-reuse resetups (kept levels re-valued, "
                "deeper levels rebuilt)")

# GEO Galerkin CSR-structure device cache (amg/aggregation/galerkin.py):
# a miss at 256^3 re-uploads ~1 GB of structure arrays per warm setup
declare_counter("amg.geo_struct_cache.hit",
                "GEO coarse CSR-structure device-cache hits")
declare_counter("amg.geo_struct_cache.miss",
                "GEO coarse CSR-structure device-cache misses "
                "(host build + device upload paid)")

# RequestBatcher (batch/queue.py)
declare_counter("batch.requests", "solve requests submitted")
declare_counter("batch.dispatches", "batched dispatches issued")
declare_counter("batch.bucket_evictions",
                "pattern buckets evicted from the RequestBatcher's "
                "bounded solver store (count or bytes budget exceeded)")
declare_counter("batch.padded_systems",
                "pad-waste systems dispatched (ladder rung minus real "
                "requests, summed over dispatches)")
declare_gauge("batch.bucket_occupancy",
              "real/padded ratio of the last dispatch (1.0 = no waste)")
declare_gauge("batch.live_buckets",
              "live pattern buckets (each holds a hierarchy + compiled "
              "programs)")

# resilience fallback engine (resilience/policy.py)
declare_counter("resilience.fallback_attempts",
                "total fallback-chain steps executed")
declare_counter("resilience.fallback.retry", "plain retry actions run")
declare_counter("resilience.fallback.rescale_retry",
                "rescale_retry actions run")
declare_counter("resilience.fallback.switch_solver",
                "switch_solver actions run")
declare_counter("resilience.fallback.escalate_sweeps",
                "escalate_sweeps actions run")

# jit retraces per solver entry point: a retrace in steady-state serving
# is a latency cliff (first-request trace cost paid again)
declare_counter("solver.retrace.solve",
                "single-solve jit cache misses (Solver.solve)")
declare_counter("solver.retrace.solve_batched",
                "batched-solve jit cache misses "
                "(BatchedSolver.solve_many)")
declare_counter("solver.retrace.distributed",
                "distributed-solve shard_map rebuilds "
                "(DistributedSolver.solve)")

# serving subsystem (amgx_tpu/serving/): the production solve service —
# continuous batching, hierarchy cache routing, AOT warm paths and
# per-tenant deadlines all report here
declare_counter("serving.requests",
                "solve requests submitted to the service")
declare_counter("serving.completed",
                "requests completed (any terminal status)")
declare_counter("serving.rejected",
                "requests rejected without solving (admission control "
                "queue bound, or reject-on-deadline action)")
declare_counter("serving.deadline_miss",
                "requests whose deadline expired before convergence "
                "(completed with DEADLINE_EXCEEDED, queued or in-flight)")
declare_counter("serving.cache.hit",
                "hierarchy-cache hits: request fingerprint matched a "
                "live bucket, so admission routes through value-resetup "
                "instead of a full AMG setup")
declare_counter("serving.cache.miss",
                "hierarchy-cache misses (full setup paid to build a "
                "new bucket)")
declare_counter("serving.cache.evictions",
                "idle buckets evicted to fit the cache byte budget")
declare_counter("serving.retrace",
                "serving-engine python traces (init/step/finish); zero "
                "in steady state and zero from the first request when "
                "the AOT store warmed the bucket")
declare_counter("serving.aot.export",
                "bucket executables exported + persisted via jax.export")
declare_counter("serving.aot.load",
                "bucket executables loaded from the AOT store (trace "
                "latency skipped)")
declare_counter("serving.aot.error",
                "AOT export/load failures degraded to plain tracing")
declare_counter("serving.deadline_action.partial",
                "expired in-flight requests completed with their "
                "current iterate")
declare_counter("serving.deadline_action.reject",
                "expired requests completed with the zero/initial "
                "iterate (reject action)")
declare_gauge("serving.queue_depth",
              "requests waiting for a bucket slot")
declare_gauge("serving.inflight",
              "requests currently occupying bucket slots")
declare_gauge("serving.live_buckets",
              "live serving buckets (each: hierarchy + engine traces)")
declare_gauge("serving.cache.bytes",
              "estimated device bytes held by live serving buckets")

# device-memory watermarks per phase (memory_info allocator statistics
# sampled at phase boundaries; the backend's own peak_bytes_in_use is
# preferred so transient in-phase maxima — Galerkin temporaries freed
# before the boundary — are captured; zero on backends reporting none)
declare_gauge("memory.setup_peak_bytes",
              "device-allocator high-water mark (bytes) sampled at "
              "setup/resetup completion")
declare_gauge("memory.solve_peak_bytes",
              "device-allocator high-water mark (bytes) sampled at "
              "solve completion")
