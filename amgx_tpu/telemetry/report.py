"""Structured solve reports (SolveReport) and their sinks.

The reference prints per-iteration solve tables and grid stats through
its registered print callback; a production consumer needs the same
information machine-readable. `SolveReport` is that object: everything
the solve already measured — per-iteration residual norms, the final
`SolveStatus`, per-level smoother/transfer/tail kernel activity, wall
times — assembled HOST-SIDE from data the solver has already pulled
(the packed stats array) plus static hierarchy metadata (shapes,
layout kinds, fusion payload presence). Building a report therefore
adds ZERO device->host transfers and never touches the traced solve
program (tests/test_telemetry.py proves both).

Sinks:
- `SolveReport.emit()` routes one machine-readable JSON line through
  `output.py`'s print callback — the reference's rank-0-only
  `amgx_distributed_output` analog (the single JAX controller plays
  rank 0 under shard_map; per-shard row/halo tallies are gathered into
  the report's `distributed` block on the controller);
- `SolveReport.to_dict()/to_json()` for programmatic consumers and the
  C API (`AMGX_solver_get_report`);
- `validate_report()` checks a report dict against the checked-in
  JSON schema (`telemetry/report_schema.json`) with a dependency-free
  validator — the `bench.py obs` acceptance gate.
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Dict, List, Optional

import numpy as np


def _json_finite(obj):
    """Map non-finite floats to None so emitted reports are STRICT
    JSON: a NAN_DETECTED solve carries NaN residuals, and bare `NaN`
    tokens (Python's default serialization) break non-Python consumers
    (JSON.parse, jq). The status/status_code fields still say WHY the
    values are null."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _json_finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_finite(v) for v in obj]
    return obj


@dataclasses.dataclass
class SolveReport:
    """Machine-readable record of one solve (see module docs)."""

    solver: str                      # root solver name
    status: str                      # SolveStatus name
    status_code: int
    iterations: int
    converged: bool
    norm0: Any                       # float, or list for block norms
    res_norm: Any
    residuals: List[Any]             # per-iteration monitored norms
    #                                  (iterations+1 entries incl. initial)
    setup_time_s: float
    solve_time_s: float
    cycle: Optional[str] = None      # AMG cycle shape when an AMG member
    #                                  is in the tree
    levels: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    tail_entry_level: Optional[int] = None   # first level the VMEM
    #                                  coarse-tail megakernel absorbed
    #                                  (None: no tail fired)
    distributed: Optional[Dict[str, Any]] = None
    counters: Optional[Dict[str, Any]] = None
    # structured grid statistics (AMG.grid_stats_dict(): per-level
    # rows/nnz/layout, grid + operator complexity) — present whenever
    # an AMG hierarchy is in the solver tree
    hierarchy: Optional[Dict[str, Any]] = None
    # convergence diagnostics (telemetry/diagnostics.py, diagnostics=1
    # knob): per-level cycle-stage norms + reduction factors, smoother
    # effectiveness, bottleneck-level attribution, asymptotic
    # convergence factor
    diagnostics: Optional[Dict[str, Any]] = None
    # per-precision accounting (precision.py solve_precision policy):
    # effective cycle dtype + outer/inner iteration counts — present
    # only when the solve_precision knob is set (None = knob unset)
    precision: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self, **kw) -> str:
        """Strict-JSON serialization: non-finite floats (NaN residuals
        of a NAN_DETECTED solve) become null instead of bare NaN
        tokens only Python accepts."""
        kw.setdefault("allow_nan", False)
        return json.dumps(_json_finite(self.to_dict()), **kw)

    def emit(self, include_counters: bool = False):
        """Route the report through the registered print callback as
        one strict-JSON line tagged `amgx_report` (rank-0-analog
        output: the single controller emits once, never per shard)."""
        from ..output import amgx_output
        d = self.to_dict()
        if include_counters and d.get("counters") is None:
            from . import metrics
            d["counters"] = metrics.snapshot()
        amgx_output(json.dumps({"amgx_report": _json_finite(d)},
                               allow_nan=False) + "\n")


# ---------------------------------------------------------------------------
# report construction
# ---------------------------------------------------------------------------


def _amg_of(solver):
    """Walk the (possibly wrapped) solver tree to the AMG hierarchy
    owner, mirroring bench.py's chain walk."""
    s = solver
    for _ in range(6):
        if s is None:
            return None
        amg = getattr(s, "amg", None)
        if amg is not None:
            return amg
        s = getattr(s, "preconditioner", None)
    return None


def _layout_kind(A) -> str:
    if getattr(A, "dia_vals", None) is not None:
        return "dia"
    if getattr(A, "swell_vals", None) is not None:
        return "swell"
    if getattr(A, "ell_vals", None) is not None:
        return "ell"
    return "csr"


def _nnz_of(A) -> Optional[int]:
    # shape metadata only: int(row_offsets[-1]) would be a device
    # transfer, which this builder must never issue
    v = getattr(A, "values", None)
    return int(np.shape(v)[0]) if v is not None else None


def _effective_dtype(amg, A) -> Optional[str]:
    """The dtype this level's operands STREAM at during the solve:
    the hierarchy's precision-policy cast when one applies, else the
    matrix's native dtype. Host metadata only."""
    eff = amg._PRECISIONS.get(getattr(amg, "precision", "double"))
    if eff is not None:
        return eff
    v = getattr(A, "values", None)
    if v is not None:
        return str(v.dtype)
    dv = getattr(A, "dia_vals", None)
    return str(dv.dtype) if dv is not None else None


def _level_table(amg):
    """Per-level static activity table: rows/nnz/layout plus which
    kernel form the cycle runs this level through — including the
    EFFECTIVE operand dtype and the fused-vs-unfused routing verdict
    (`fused_routing`), so a config that falls off the fused path
    (e.g. a dtype the kernel whitelist declines) is visible in one
    report read instead of silently rerouting. Everything reads
    object metadata and payloads memoized at setup — no device work.
    A hierarchy in an unexpected state (sharded build, partially
    stripped) degrades to the bare rows/layout columns.

    Memoized on the hierarchy: the table is structure-only, so it
    changes only when the level list is rebuilt (setup / structure
    resetup — a NEW list object) or the tail boundary is first
    recorded; per-solve report construction then costs a list copy."""
    from ..ops.pallas_spmv import SMOOTH_DTYPES
    levels = getattr(amg, "levels", None) or []
    tail0 = getattr(amg, "_tail_entry_level", None)
    key = (id(levels), len(levels), tail0)
    cached = getattr(amg, "_telemetry_level_cache", None)
    if cached is not None and cached[0] == key:
        return [dict(r) for r in cached[1]], tail0
    rows: List[Dict[str, Any]] = []
    for lvl, level in enumerate(levels):
        A = level.A
        row: Dict[str, Any] = {
            "level": lvl,
            "rows": int(A.num_rows),
            "nnz": _nnz_of(A),
            "layout": _layout_kind(A),
        }
        try:
            ld = level.level_data()
        except Exception:
            ld = None
        smd = ld.get("smoother") if isinstance(ld, dict) else None
        fused_sm = bool(isinstance(smd, dict)
                        and ("fused" in smd or "dist_fused" in smd))
        fused_xf = bool(isinstance(ld, dict) and "xfer" in ld)
        row["fused_smoother"] = fused_sm
        row["fused_transfers"] = fused_xf
        edt = _effective_dtype(amg, A)
        row["dtype"] = edt
        dtype_ok = edt in SMOOTH_DTYPES
        if not fused_sm:
            row["fused_routing"] = "unfused"
        elif dtype_ok:
            row["fused_routing"] = "fused"
        else:
            # payload built but the kernel dtype gate declines: the
            # cycle composes unfused (counted fusion.declined_dtype
            # at trace time by ops/smooth.py)
            row["fused_routing"] = "declined_dtype"
        # a fully fused aggregation/DIA level does its whole per-visit
        # cycle work (presmooth+restrict, prolong+postsmooth) in
        # exactly two pallas_calls (PR 5); levels inside the VMEM
        # coarse tail run in the tail's single kernel instead
        row["kernels_per_visit"] = 2 if (fused_sm and fused_xf
                                         and dtype_ok) else None
        rows.append(row)
    coarsest = getattr(amg, "coarsest_A", None)
    if coarsest is not None and levels:
        rows.append({
            "level": len(levels),
            "rows": int(coarsest.num_rows),
            "nnz": _nnz_of(coarsest),
            "layout": _layout_kind(coarsest),
            "fused_smoother": False,
            "fused_transfers": False,
            "kernels_per_visit": None,
            "coarse_solver": getattr(amg.coarse_solver, "name", None),
        })
    tail = getattr(amg, "_tail_entry_level", None)
    if tail is not None:
        for row in rows:
            if row["level"] >= tail:
                row["kind"] = "vmem_tail"
                row["kernels_per_visit"] = None
    try:
        amg._telemetry_level_cache = (key, rows)
    except Exception:
        pass
    return [dict(r) for r in rows], tail


def _scalar(v):
    a = np.asarray(v)
    return a.tolist() if a.ndim else float(a)


def build_report(solver, result, hist=None,
                 distributed: Optional[Dict[str, Any]] = None,
                 diagnostics: Optional[Dict[str, Any]] = None,
                 precision: Optional[Dict[str, Any]] = None
                 ) -> SolveReport:
    """Assemble a SolveReport from a finished SolveResult-shaped record
    and the solver tree's static metadata. `hist` overrides the
    result's stored residual history (the solve path passes the already
    unpacked numpy history even when store_res_history=0).
    `diagnostics` is the derived convergence-diagnostics block when the
    probe ran (telemetry/diagnostics.py). Safe under
    jax.transfer_guard('disallow'): only host data and shapes are
    read (grid_stats_dict included — it reads shape metadata only)."""
    hist = result.res_history if hist is None else hist
    residuals = [] if hist is None else np.asarray(hist).tolist()
    amg = _amg_of(solver)
    levels: List[Dict[str, Any]] = []
    tail = None
    cycle = None
    hierarchy = None
    if amg is not None and distributed is None:
        levels, tail = _level_table(amg)
        cycle = getattr(amg, "cycle_name", None)
    elif amg is not None:
        cycle = getattr(amg, "cycle_name", None)
    if amg is not None:
        try:
            hierarchy = amg.grid_stats_dict()
        except Exception:
            hierarchy = None   # partially built / stripped hierarchy
    return SolveReport(
        solver=str(getattr(solver, "name", type(solver).__name__)),
        status=result.status if isinstance(getattr(result, "status", None),
                                           str) else str(result.status),
        status_code=int(result.status_code),
        iterations=int(result.iterations),
        converged=bool(result.converged),
        norm0=_scalar(result.norm0),
        res_norm=_scalar(result.res_norm),
        residuals=residuals,
        setup_time_s=float(getattr(result, "setup_time", 0.0)),
        solve_time_s=float(getattr(result, "solve_time", 0.0)),
        cycle=cycle,
        levels=levels,
        tail_entry_level=tail,
        distributed=distributed,
        hierarchy=hierarchy,
        diagnostics=diagnostics,
        precision=precision,
    )


# ---------------------------------------------------------------------------
# schema validation (dependency-free subset validator)
# ---------------------------------------------------------------------------

_SCHEMA_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "report_schema.json")


def load_schema() -> Dict[str, Any]:
    with open(_SCHEMA_PATH) as f:
        return json.load(f)


_TYPES = {
    "object": dict, "array": list, "string": str, "boolean": bool,
    "integer": int, "null": type(None),
}


def _type_ok(value, tname: str) -> bool:
    if tname == "number":
        return isinstance(value, (int, float)) \
            and not isinstance(value, bool)
    if tname == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    return isinstance(value, _TYPES[tname])


def _validate(value, schema: Dict[str, Any], path: str,
              errors: List[str]):
    t = schema.get("type")
    if t is not None:
        names = t if isinstance(t, list) else [t]
        if not any(_type_ok(value, n) for n in names):
            errors.append(f"{path}: expected {names}, got "
                          f"{type(value).__name__}")
            return
    if "enum" in schema and value not in schema["enum"]:
        errors.append(f"{path}: {value!r} not in enum {schema['enum']}")
    if isinstance(value, dict):
        for req in schema.get("required", ()):
            if req not in value:
                errors.append(f"{path}: missing required key {req!r}")
        for key, sub in schema.get("properties", {}).items():
            if key in value:
                _validate(value[key], sub, f"{path}.{key}", errors)
    if isinstance(value, list) and "items" in schema:
        for i, item in enumerate(value):
            _validate(item, schema["items"], f"{path}[{i}]", errors)


def validate_report(d: Dict[str, Any],
                    schema: Optional[Dict[str, Any]] = None) -> List[str]:
    """Validate a report dict against the checked-in schema; returns
    the list of violations (empty = valid). Implements the subset of
    JSON Schema the checked-in schema uses (type unions, required,
    properties, items, enum) so validation needs no extra dependency."""
    errors: List[str] = []
    _validate(d, schema if schema is not None else load_schema(),
              "report", errors)
    return errors
