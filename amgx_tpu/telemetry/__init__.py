"""Unified telemetry subsystem.

One place every layer reports into (the reference exposes the same
information through nvtx ranges, the AMGX_timer tree, and the verbose
solve tables; ours is structured and machine-readable):

- `telemetry.metrics` — process-wide counter/gauge/histogram registry
  (cache hit/miss, setup-routing, batcher occupancy, fallback events,
  jit retraces, memory watermarks, per-tenant serving-latency
  distributions); dump with `metrics.snapshot()` / the C API's
  `AMGX_read_metrics`, or scrape the whole registry as an OpenMetrics
  text exposition (`metrics.to_openmetrics()` /
  `AMGX_read_metrics_openmetrics`).
- `telemetry.diagnostics` — opt-in convergence diagnostics
  (`diagnostics=1`): an in-trace probe cycle records per-level
  residual norms at the cycle stages, and host-side derivation turns
  them into reduction factors, smoother effectiveness, an asymptotic
  convergence-factor estimate and a bottleneck-level attribution on
  `SolveReport.diagnostics`.
- `telemetry.spans` — hierarchical host spans behind
  `profiling.trace_region`, exported as Chrome/Perfetto trace-event
  JSON (`spans.export_chrome_trace`); `telemetry_sync=1` fences device
  work at span boundaries so host spans bound device occupancy.
- `telemetry.flightrec` — crash-surviving flight recorder: a bounded
  append-and-rotate structured event log of state transitions (bucket
  builds/quarantines/requeues, shed decisions with their feasibility
  estimate, fallback-chain hops, resetup routing, chaos injections),
  each stamped with the request trace id; on a BREAKDOWN the serving
  layer dumps the last-N events through output.py, and
  `tools/flightrec.py` pretty-prints + journal-correlates a log for
  postmortems.
- `telemetry.report` — `SolveReport`: in-trace solve metrics (riding
  the monitor's packed stats array at zero added device->host syncs)
  plus static per-level kernel-activity metadata, attached to
  `SolveResult.report` / `BatchedSolveResult.reports` / distributed
  results and reachable from the C API (`AMGX_solver_get_report`);
  validated against `report_schema.json`.

The `telemetry` config knob (default 1) gates report construction and
memory-watermark sampling per solver; counters and spans are always on
(dict updates — the in-trace solve program is NEVER touched either
way, so `telemetry=0` and `telemetry=1` compile identical XLA).
"""
from __future__ import annotations

from . import diagnostics, flightrec, metrics, spans  # noqa: F401
from .report import SolveReport, build_report, validate_report  # noqa: F401
