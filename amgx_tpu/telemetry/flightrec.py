"""Crash-surviving flight recorder: bounded structured event log.

A crashed service leaves a data journal (serving/journal.py) that says
WHAT was in flight, but nothing that says WHY the process died — the
shed decisions, build failures, quarantines, fallback hops and chaos
injections leading up to the crash are gone with the process. The
flight recorder is that missing event history: a bounded, append-and-
rotate structured log of STATE TRANSITIONS, kept in a process-local
ring always and mirrored to disk per event when a directory is
configured (the `flightrec_dir` config knob / `AMGX_TPU_FLIGHTREC_DIR`
env), so a postmortem can read the last seconds of a dead process.

Recorded event classes (each stamped with the request trace id when
one is in scope, linking the event to the Perfetto flow chain and the
journal record of the request that caused it):

- serving: bucket builds / build failures + retries, quarantines,
  slot salvage/requeue, shed decisions WITH their feasibility
  estimate, deadline misses (serving/service.py);
- resilience: fallback-chain hops (resilience/policy.py) and armed /
  fired chaos injections (resilience/faultinject.py);
- AMG: setup routing — full build vs value/structure resetup vs
  restored-from-snapshot (amg/hierarchy.py).

Durability discipline mirrors the journal's: one `write()` of one
JSON line per event + flush (a torn final line is the crash itself),
rotation via atomic `os.replace` (the previous generation survives as
`flight.log.1`), and corruption-tolerant reads that DROP unparseable
lines (counted, `flightrec.dropped`) instead of wedging the
postmortem. On a BREAKDOWN completion the serving layer dumps the
last-N events through output.py's print callback; `tools/flightrec.py`
pretty-prints a log directory and correlates it with a solve journal.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

_LOG_NAME = "flight.log"


def format_event(e: Dict[str, Any]) -> str:
    """One aligned human line per event (shared by the BREAKDOWN dump,
    tools/flightrec.py and examples/chaos_demo.py)."""
    t = e.get("t")
    clock = time.strftime("%H:%M:%S", time.localtime(t)) \
        if isinstance(t, (int, float)) else "--:--:--"
    trace = e.get("trace") or "-"
    extras = " ".join(
        f"{k}={v}" for k, v in sorted(e.items())
        if k not in ("seq", "t", "kind", "trace") and v is not None)
    return (f"[{e.get('seq', '?'):>6}] {clock} "
            f"{str(e.get('kind', '?')):<22} trace={trace} {extras}")


class FlightRecorder:
    """Bounded event recorder (see module docs). Thread-safe; the
    in-memory ring always records, the file mirror is optional."""

    def __init__(self, directory: Optional[str] = None,
                 max_events: int = 4096, rotate_events: int = 2048):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=int(max_events))
        self._seq = 0
        self.rotate_events = int(rotate_events)
        self._dir: Optional[str] = None
        self._fh = None
        self._lines = 0
        if directory:
            self.open(directory)

    # -- file backing ------------------------------------------------------
    @property
    def directory(self) -> Optional[str]:
        return self._dir

    def open(self, directory: str):
        """Attach (or switch) the disk mirror; the in-memory ring is
        kept. Appends to an existing log so a restarted process keeps
        extending the same history (sequence numbers restart per
        process; the wall-clock stamp orders across incarnations)."""
        with self._lock:
            self._close_locked()
            self._dir = str(directory)
            os.makedirs(self._dir, exist_ok=True)
            path = os.path.join(self._dir, _LOG_NAME)
            self._lines = 0
            if os.path.exists(path):
                try:
                    with open(path, "rb") as f:
                        self._lines = sum(1 for _ in f)
                except OSError:
                    pass
            self._fh = open(path, "a")

    def close(self):
        with self._lock:
            self._close_locked()
            self._dir = None

    def _close_locked(self):
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def _rotate_locked(self):
        """Atomic generation swap: flight.log -> flight.log.1 (the
        previous .1 is dropped), fresh flight.log. Bounds the on-disk
        history to <= 2 * rotate_events events while always keeping at
        least rotate_events of lookback."""
        path = os.path.join(self._dir, _LOG_NAME)
        self._close_locked()
        try:
            os.replace(path, path + ".1")
        except OSError:
            pass
        self._fh = open(path, "a")
        self._lines = 0

    # -- write path --------------------------------------------------------
    def record(self, kind: str, trace: Optional[str] = None,
               **fields) -> Dict[str, Any]:
        """Append one event: {'seq', 't' (epoch seconds), 'kind',
        'trace', **fields}. One line-write + flush when a directory is
        attached — the crash-surviving part; a torn final line is
        dropped (and counted) by the reader."""
        from . import metrics as _tm
        with self._lock:
            self._seq += 1
            ev = {"seq": self._seq, "t": time.time(),
                  "kind": str(kind), "trace": trace}
            for k, v in fields.items():
                if v is not None:
                    ev[k] = v
            self._ring.append(ev)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(ev, allow_nan=False,
                                              default=str) + "\n")
                    self._fh.flush()
                    self._lines += 1
                    if self._lines >= self.rotate_events:
                        self._rotate_locked()
                except (OSError, ValueError):
                    pass             # degraded durability, never a raise
        _tm.inc("flightrec.events")
        return ev

    # -- read path ---------------------------------------------------------
    def events(self, last: Optional[int] = None,
               kind: Optional[str] = None,
               trace: Optional[str] = None,
               since_seq: int = 0) -> List[Dict[str, Any]]:
        """This process's in-memory ring (oldest first), optionally
        filtered by kind prefix / trace id / minimum sequence."""
        with self._lock:
            evs = list(self._ring)
        if since_seq:
            evs = [e for e in evs if e.get("seq", 0) > since_seq]
        if kind is not None:
            evs = [e for e in evs
                   if str(e.get("kind", "")).startswith(kind)]
        if trace is not None:
            evs = [e for e in evs if e.get("trace") == trace]
        if last is not None:
            evs = evs[-int(last):]
        return evs

    @property
    def last_seq(self) -> int:
        return self._seq

    def reset(self):
        with self._lock:
            self._ring.clear()

    @staticmethod
    def load(directory: str) -> List[Dict[str, Any]]:
        """Read a flight-recorder directory back (rotated generation
        first, then the live log), DROPPING corrupt lines — a torn
        final write or bit-flipped record costs one event, never the
        postmortem. Drops are counted (`flightrec.dropped`)."""
        from . import metrics as _tm
        out: List[Dict[str, Any]] = []
        dropped = 0
        for name in (_LOG_NAME + ".1", _LOG_NAME):
            path = os.path.join(str(directory), name)
            try:
                with open(path) as f:
                    lines = f.readlines()
            except OSError:
                continue
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                    if not isinstance(ev, dict):
                        raise ValueError("not an object")
                except ValueError:
                    dropped += 1
                    continue
                out.append(ev)
        if dropped:
            _tm.inc("flightrec.dropped", dropped)
        return out

    # -- postmortem dump ---------------------------------------------------
    def dump_recent(self, n: int = 16, reason: str = ""):
        """Print the last `n` events through output.py's callback —
        the on-BREAKDOWN postmortem trail. Silent when nothing has
        been recorded."""
        evs = self.events(last=n)
        if not evs:
            return
        from ..output import amgx_output
        head = f"flight recorder (last {len(evs)} events"
        if reason:
            head += f"; {reason}"
        amgx_output(head + "):\n")
        for e in evs:
            amgx_output("  " + format_event(e) + "\n")


# ---------------------------------------------------------------------------
# the process-wide recorder
# ---------------------------------------------------------------------------

_REC = FlightRecorder()
_ENV_CHECKED = False


def _check_env():
    """Attach the disk mirror from AMGX_TPU_FLIGHTREC_DIR on first
    use (the config-free path; SolveService also configures from the
    `flightrec_dir` knob)."""
    global _ENV_CHECKED
    if _ENV_CHECKED:
        return
    _ENV_CHECKED = True
    d = os.environ.get("AMGX_TPU_FLIGHTREC_DIR", "").strip()
    if d and _REC.directory is None:
        try:
            _REC.open(d)
        except OSError:
            pass


def configure(directory: Optional[str]):
    """Attach/detach the process recorder's disk mirror."""
    global _ENV_CHECKED
    _ENV_CHECKED = True
    if directory:
        _REC.open(directory)
    else:
        _REC.close()


def recorder() -> FlightRecorder:
    return _REC


def record(kind: str, trace: Optional[str] = None, **fields):
    _check_env()
    return _REC.record(kind, trace=trace, **fields)


def events(**kw) -> List[Dict[str, Any]]:
    return _REC.events(**kw)


def last_seq() -> int:
    return _REC.last_seq


def reset():
    _REC.reset()


def dump_recent(n: int = 16, reason: str = ""):
    _REC.dump_recent(n=n, reason=reason)


def load(directory: str) -> List[Dict[str, Any]]:
    return FlightRecorder.load(directory)
