"""JAX cross-version compatibility shims.

The supported JAX span moved `shard_map` from
`jax.experimental.shard_map` (<= 0.4.x, replication check spelled
`check_rep`) to `jax.shard_map` (>= 0.5, spelled `check_vma`). All
in-tree callers import from here and use the modern spelling; the shim
translates for older runtimes.
"""
from __future__ import annotations

try:
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover - version dependent
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    kw = {} if check_vma is None else {_CHECK_KW: check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def enable_x64(enabled: bool = True):
    """`jax.enable_x64(bool)` context manager; on older runtimes it maps
    to jax.experimental.enable_x64/disable_x64."""
    import jax
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(enabled)
    from jax.experimental import disable_x64, enable_x64 as _enable
    return _enable() if enabled else disable_x64()
