"""Eigensolver subsystem.

TPU-native analog of the reference's secondary eigensolver product
(src/eigensolvers/ ~3k LoC; C API include/amgx_eig_c.h:18-26). The
registry names match src/eigensolvers/eigensolvers.cu:38-48:

    SINGLE_ITERATION / POWER_ITERATION / PAGERANK / INVERSE_ITERATION
    SUBSPACE_ITERATION, LANCZOS, ARNOLDI, LOBPCG, JACOBI_DAVIDSON

Usage (AMG_EigenSolver analog, src/amg_eigensolver.cu)::

    cfg = Config.from_string("eig_solver=LANCZOS, eig_which=smallest, "
                             "eig_eigenvector=1")
    es = create_eigensolver(cfg)
    es.setup(A)
    res = es.solve()          # -> EigenResult
"""
from .base import (EigenResult, EigenSolver, create_eigensolver,
                   make_eigensolver)
from .operators import (DeflatedOperator, MatrixOperator, Operator,
                        PageRankOperator, ShiftedOperator, SolveOperator)
from . import power, krylov, block, jacobi_davidson  # noqa: F401 (register)

__all__ = [
    "EigenResult", "EigenSolver", "create_eigensolver", "make_eigensolver",
    "Operator", "MatrixOperator", "ShiftedOperator", "DeflatedOperator",
    "SolveOperator", "PageRankOperator",
]
