"""Single-iteration (power-method family) eigensolver.

TPU-native analog of SingleIteration_EigenSolver
(src/eigensolvers/single_iteration_eigensolver.cu). One operator apply
per iteration + normalization + Rayleigh quotient. As in the reference
(solver_setup :187-214), the operator depends on `eig_which`:

- largest  -> A (shifted by eig_shift if set): classic power iteration;
- smallest -> SolveOperator wrapping the solver configured under the
  "solver" parameter (inverse iteration, :198-209);
- pagerank -> PageRankOperator (:193-196); the iterate is additionally
  L1-normalized so it stays a probability distribution.

Registered as SINGLE_ITERATION / POWER_ITERATION / INVERSE_ITERATION /
PAGERANK (src/eigensolvers/eigensolvers.cu:38-43).
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import registry
from ..errors import BadParametersError
from ..ops import blas
from .base import EigenSolver
from .operators import PageRankOperator, SolveOperator


@registry.eigensolvers.register("SINGLE_ITERATION")
@registry.eigensolvers.register("POWER_ITERATION")
@registry.eigensolvers.register("INVERSE_ITERATION")
@registry.eigensolvers.register("PAGERANK")
class SingleIterationEigenSolver(EigenSolver):

    def __init__(self, cfg, scope="default", name="POWER_ITERATION"):
        super().__init__(cfg, scope, name=name)
        if name.upper() == "INVERSE_ITERATION":
            self.which = "smallest"
        elif name.upper() == "PAGERANK":
            self.which = "pagerank"

    def make_operator(self):
        if self.which == "pagerank":
            return PageRankOperator(self.A, self.damping)
        if self.which == "smallest":
            # inverse iteration: apply (A - shift I)^{-1} via the nested
            # solver configured under "solver" (reference :198-209)
            from ..solvers.base import make_solver
            sname, sscope = self.cfg.get_solver("solver", self.scope)
            if sname.upper() in ("NOSOLVER", "DUMMY"):
                raise BadParametersError(
                    "INVERSE_ITERATION needs a 'solver' parameter naming "
                    "the inner linear solver")
            solver = make_solver(sname, self.cfg, sscope)
            A = self.A
            if self.shift != 0.0:
                # build A - shift*I explicitly so the inner solver
                # factors/smooths the shifted matrix (reference :205-206)
                import numpy as np
                if A.has_external_diag:
                    A = A.with_values(A.values, diag=A.diag - self.shift)
                else:
                    if np.any(np.asarray(A.diag_idx) < 0):
                        raise BadParametersError(
                            "eig_shift needs a stored diagonal in every row")
                    vals = A.values.at[A.diag_idx].add(-self.shift)
                    A = A.with_values(vals)
            solver.setup(A)
            self._inner_solver = solver
            return SolveOperator(solver)
        return super().make_operator()

    def unshift(self, lam):
        if self.which == "smallest":
            # operator eigenvalue is 1/(lambda - shift)
            return self.shift + 1.0 / lam
        if self.which == "pagerank":
            return lam
        return super().unshift(lam)

    # -- pure pieces -----------------------------------------------------
    def solve_init(self, data, x0):
        if self.which == "pagerank":
            v = jnp.abs(x0)
            v = v / jnp.maximum(blas.nrm1(v), 1e-30)
        else:
            v = x0 / jnp.maximum(blas.nrm2(x0), 1e-30)
        one = jnp.ones((1,), x0.dtype)
        return {"v": v, "lambdas": one,
                "resid": jnp.full((1,), jnp.inf, x0.dtype)}

    def solve_iteration(self, data, state):
        v = state["v"]
        w = self.op.apply(data["op"], v)
        # Rayleigh quotient; the pagerank iterate is L1- (not L2-)
        # normalized, so divide by v.v explicitly
        vv = blas.dot(v, v)
        lam = blas.dot(v, w) / jnp.maximum(vv, 1e-30)
        r = w - lam * v
        resid = blas.nrm2(r) / jnp.sqrt(jnp.maximum(vv, 1e-30))
        if self.which == "pagerank":
            nrm = blas.nrm1(w)
        else:
            nrm = blas.nrm2(w)
        v_new = w / jnp.maximum(nrm, 1e-30)
        return {"v": v_new, "lambdas": lam[None], "resid": resid[None]}

    def finalize(self, data, state):
        vec = state["v"][:, None] if self.want_vectors or \
            self.which == "pagerank" else None
        return state["lambdas"], vec, state["resid"]
