"""Block eigensolvers: LOBPCG and subspace iteration.

TPU-native analogs of src/eigensolvers/lobpcg_eigensolver.cu and
subspace_iteration_eigensolver.cu. Block methods are the natural TPU
shape: every step is (n, k) matrix panels flowing through batched SpMV,
tall-skinny QR (`jnp.linalg.qr`) and small dense Rayleigh-Ritz
eigenproblems (`jnp.linalg.eigh`) — all MXU work, all in one jitted
while_loop.

LOBPCG optionally applies a preconditioner built from the standard
solver tree (the "preconditioner" parameter in the eigensolver scope) to
the residual block — the analog of the reference wiring a Solver as the
LOBPCG preconditioner.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import registry
from .base import EigenSolver


def _block_apply(op, data, X):
    """Apply the operator to each column of (n, k) X."""
    return jax.vmap(lambda c: op.apply(data, c), in_axes=1, out_axes=1)(X)


def _orthonormalize(X):
    Q, _ = jnp.linalg.qr(X)
    return Q


def _rayleigh_ritz(op_data, op, S, k: int, which: str):
    """Rayleigh-Ritz on the subspace spanned by S's columns. Returns
    (lam (k,), X (n,k), AX (n,k))."""
    Q = _orthonormalize(S)
    AQ = _block_apply(op, op_data, Q)
    G = Q.T @ AQ
    G = 0.5 * (G + G.T)
    lam, W = jnp.linalg.eigh(G)            # ascending
    m = G.shape[0]
    if which == "smallest":
        idx = jnp.arange(k)
    else:
        idx = jnp.arange(m - 1, m - 1 - k, -1)
    W_k = W[:, idx]
    return lam[idx], Q @ W_k, AQ @ W_k


@registry.eigensolvers.register("SUBSPACE_ITERATION")
class SubspaceIterationEigenSolver(EigenSolver):
    """Block power iteration with periodic Rayleigh-Ritz
    (subspace_iteration_eigensolver.cu)."""

    def solver_setup(self):
        from ..errors import BadParametersError
        if self.which == "smallest":
            # power steps amplify the dominant subspace; Rayleigh-Ritz
            # residuals would converge on dominant-subspace pairs that
            # are nowhere near the smallest eigenvalues
            raise BadParametersError(
                "SUBSPACE_ITERATION computes the dominant (largest) "
                "eigenpairs; use LANCZOS or LOBPCG for eig_which=smallest")
        k = self.wanted_count
        m = self.subspace_size
        self.block = min(max(m, k + 2) if m > 0 else max(2 * k, k + 2),
                         self.A.num_rows)

    def solve_init(self, data, x0):
        n, p, dt = self.A.num_rows, self.block, x0.dtype
        k = self.wanted_count
        rng = np.random.default_rng(7)
        X = jnp.asarray(rng.standard_normal((n, p)), dt)
        X = X.at[:, 0].set(x0)
        return {"X": _orthonormalize(X),
                "lambdas": jnp.zeros((k,), dt),
                "resid": jnp.full((k,), jnp.inf, dt)}

    def solve_iteration(self, data, state):
        k = self.wanted_count
        X = state["X"]
        AX = _block_apply(self.op, data["op"], X)
        lam, Xr, AXr = _rayleigh_ritz(data["op"], self.op, AX, k,
                                      self.which)
        R = AXr - Xr * lam[None, :]
        resid = jnp.linalg.norm(R, axis=0)
        # refill the non-wanted part of the block from A X (power step)
        Xn = jnp.concatenate([Xr, AX[:, k:self.block]], axis=1) \
            if self.block > k else Xr
        return {"X": _orthonormalize(Xn), "lambdas": lam, "resid": resid}

    def finalize(self, data, state):
        vec = state["X"][:, : self.wanted_count] if self.want_vectors \
            else None
        return state["lambdas"], vec, state["resid"]


@registry.eigensolvers.register("LOBPCG")
class LOBPCGEigenSolver(EigenSolver):
    """Locally optimal block preconditioned CG (lobpcg_eigensolver.cu).
    State blocks X (iterates), P (search directions); each step does
    Rayleigh-Ritz on span[X, W, P] with W the (preconditioned)
    residuals."""

    def solver_setup(self):
        self.k = max(self.wanted_count, 1)
        self.precond = None
        pname, pscope = self.cfg.get_solver("preconditioner", self.scope)
        if pname.upper() not in ("NOSOLVER", "DUMMY"):
            from ..solvers.base import make_solver
            self.precond = make_solver(pname, self.cfg, pscope)
            self.precond._owns_scaling = False
            self.precond.setup(self.A)

    def solve_data(self):
        d = super().solve_data()
        if self.precond is not None:
            d["precond"] = self.precond.solve_data()
        return d

    def solve_init(self, data, x0):
        n, k, dt = self.A.num_rows, self.k, x0.dtype
        rng = np.random.default_rng(11)
        X = jnp.asarray(rng.standard_normal((n, k)), dt)
        X = X.at[:, 0].set(x0)
        X = _orthonormalize(X)
        return {"X": X, "P": jnp.zeros((n, k), dt),
                "lambdas": jnp.zeros((k,), dt),
                "resid": jnp.full((k,), jnp.inf, dt)}

    def solve_iteration(self, data, state):
        k = self.k
        X, P = state["X"], state["P"]
        AX = _block_apply(self.op, data["op"], X)
        lam = jnp.sum(X * AX, axis=0)        # Rayleigh quotients
        R = AX - X * lam[None, :]
        if self.precond is not None:
            W = jax.vmap(lambda c: self.precond.apply(data["precond"], c),
                         in_axes=1, out_axes=1)(R)
        else:
            W = R
        S = jnp.concatenate([X, W, P], axis=1)
        lam_k, Xn, AXn = _rayleigh_ritz(data["op"], self.op, S, k,
                                        self.which)
        # residuals of the POST-update eigenpairs (AXn is already in
        # hand from Rayleigh-Ritz, so this costs nothing extra)
        resid = jnp.linalg.norm(AXn - Xn * lam_k[None, :], axis=0)
        # new search directions: component of the update orthogonal to X
        Pn = Xn - X @ (X.T @ Xn)
        pn = jnp.linalg.norm(Pn, axis=0, keepdims=True)
        Pn = jnp.where(pn > 1e-12, Pn / jnp.maximum(pn, 1e-30), 0.0)
        return {"X": Xn, "P": Pn, "lambdas": lam_k, "resid": resid}

    def finalize(self, data, state):
        vec = state["X"] if self.want_vectors else None
        return state["lambdas"], vec, state["resid"]
