"""Operator adapters for eigensolvers.

TPU-native analog of the reference operator hierarchy
(include/operators/operator.h:14, src/operators/*.cu). An Operator is a
linear action `y = Op(x)` the Krylov/power iterations consume; the
reference's virtual `apply(v, res, view)` becomes a *pure function*
`apply(data, x)` over a device-data pytree so whole eigensolver loops
trace into one XLA program.

Adapters (reference files):
- MatrixOperator      — plain SpMV.
- ShiftedOperator     — (A - sigma I) x   (src/operators/shifted_operator.cu)
- DeflatedOperator    — A x - V diag(l) V^T x
                        (src/operators/deflated_multiply_operator.cu)
- SolveOperator       — approximate A^{-1} x via a nested Solver
                        (src/operators/solve_operator.cu:29-42)
- PageRankOperator    — alpha * H^T x + (a . x) b, the Google-matrix
                        action (src/operators/pagerank_operator.cu:21-36)
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..matrix import CsrMatrix
from ..ops.spmv import spmv
from ..ops.transpose import transpose


class Operator:
    """Linear action with a device-data pytree (pure-function apply)."""

    def data(self):
        raise NotImplementedError

    def apply(self, data, x):
        raise NotImplementedError


class MatrixOperator(Operator):
    def __init__(self, A: CsrMatrix):
        self.A = A if A.initialized else A.init()
        self.num_rows = A.num_rows

    def data(self):
        return {"A": self.A}

    def apply(self, data, x):
        return spmv(data["A"], x)


class ShiftedOperator(Operator):
    """(inner - sigma I) x — spectral shift (shifted_operator.cu)."""

    def __init__(self, inner: Operator, sigma: float):
        self.inner = inner
        self.sigma = sigma
        self.num_rows = inner.num_rows

    def data(self):
        return {"inner": self.inner.data(),
                "sigma": jnp.asarray(self.sigma)}

    def apply(self, data, x):
        y = self.inner.apply(data["inner"], x)
        return y - data["sigma"] * x


class DeflatedOperator(Operator):
    """inner(x) - V diag(lambdas) V^T x: deflates converged eigenpairs out
    of the spectrum (deflated_multiply_operator.cu)."""

    def __init__(self, inner: Operator, lambdas, V):
        self.inner = inner
        self.lambdas = jnp.asarray(lambdas)
        self.V = jnp.asarray(V)           # (n, k) orthonormal columns
        self.num_rows = inner.num_rows

    def data(self):
        return {"inner": self.inner.data(), "lambdas": self.lambdas,
                "V": self.V}

    def apply(self, data, x):
        y = self.inner.apply(data["inner"], x)
        c = data["V"].T @ x
        return y - data["V"] @ (data["lambdas"] * c)


class SolveOperator(Operator):
    """Approximate inverse action via a nested Solver's fixed-sweep
    preconditioner application (solve_operator.cu:29-42). Used by
    INVERSE_ITERATION for the smallest eigenpair."""

    def __init__(self, solver):
        self.solver = solver               # a set-up solvers.base.Solver
        self.num_rows = solver.A.num_rows

    def data(self):
        return {"sdata": self.solver.solve_data()}

    def apply(self, data, x):
        return self.solver.apply(data["sdata"], x)


class PageRankOperator(Operator):
    """Google-matrix action on the stationary-distribution iterate:

        y = alpha * H^T x + (a . x) * b

    with H the row-stochastic link matrix built from A's adjacency,
    a = alpha * dangling + (1 - alpha) * ones (teleport + dangling-node
    correction) and b = ones/n — exactly the reference apply
    (pagerank_operator.cu:30-36: SpMV, scal, dot, axpy). The dominant
    eigenvector (eigenvalue 1) is the PageRank vector.
    """

    def __init__(self, A: CsrMatrix, damping: float = 0.85):
        n = A.num_rows
        # out-degree row normalization of the adjacency (host, once)
        ro = np.asarray(A.row_offsets)
        vals = np.abs(np.asarray(A.values, dtype=np.float64))
        row_ids = np.repeat(np.arange(n), np.diff(ro))
        deg = np.zeros(n)
        np.add.at(deg, row_ids, vals)
        dangling = (deg == 0.0).astype(vals.dtype)
        inv_deg = np.where(deg > 0, 1.0 / np.maximum(deg, 1e-300), 0.0)
        Hvals = vals * inv_deg[row_ids]
        H = CsrMatrix.from_scipy_like(
            A.row_offsets, A.col_indices, Hvals.astype(np.asarray(A.values).dtype),
            n, n)
        self.Ht = transpose(H).init()
        self.alpha = damping
        self.a = jnp.asarray(damping * dangling + (1.0 - damping),
                             dtype=self.Ht.dtype)
        self.b = jnp.full((n,), 1.0 / n, dtype=self.Ht.dtype)
        self.num_rows = n

    def data(self):
        return {"Ht": self.Ht, "a": self.a, "b": self.b}

    def apply(self, data, x):
        y = self.alpha * spmv(data["Ht"], x)
        gamma = jnp.dot(data["a"], x)
        return y + gamma * data["b"]
