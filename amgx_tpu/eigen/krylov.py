"""Krylov eigensolvers: Lanczos (symmetric) and Arnoldi (general).

TPU-native analogs of src/eigensolvers/lanczos_eigensolver.cu and
arnoldi_eigensolver.cu. Static-shape Krylov bases (m+1, n) built by a
`lax.fori_loop` — one operator apply + orthogonalization per step, the
same structure as the reference's per-iteration kernels — then the small
projected eigenproblem:

- Lanczos: tridiagonal T, solved in-trace with `jnp.linalg.eigh`; the
  driver's while_loop restarts with the best Ritz vector until the
  eigenpair residual bound |beta_m * s_m| meets eig_tolerance.
- Arnoldi: Hessenberg H, solved on the host with numpy `eig` after the
  device loop — the reference defers the same m x m problem to LAPACK
  geev (src/amgx_lapack.cu); it is scalar-serial with no TPU-parallel
  structure.

Both use classical Gram-Schmidt applied twice (full reorthogonalization):
on a TPU, V @ w and V.T @ c are batched matvecs that ride the MXU, so
full reorth is cheaper than the reference's selective schemes while being
more robust.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import registry
from .base import EigenResult, EigenSolver


def _krylov_dim(self) -> int:
    m = self.subspace_size
    if m is None or m <= 0:
        m = max(2 * self.wanted_count + 18, 20)
    return min(m, self.A.num_rows)


@registry.eigensolvers.register("LANCZOS")
class LanczosEigenSolver(EigenSolver):
    """Symmetric Lanczos with full reorthogonalization and thick restart
    (lanczos_eigensolver.cu). Each driver iteration expands the basis
    from the k kept Ritz vectors (plus the residual direction) to m
    vectors with the Lanczos chain w = A v_j orthogonalized against ALL
    built columns, then Rayleigh-Ritzes with an explicitly projected
    G = V (A V)^T — the arrowhead-T bookkeeping of classic thick-restart
    Lanczos replaced by one extra batched SpMV panel, which on a TPU is
    MXU-cheap and numerically airtight."""

    def solver_setup(self):
        self.m = _krylov_dim(self)
        if self.m <= self.wanted_count + 1:
            self.m = min(self.wanted_count + 2, self.A.num_rows)

    def solve_init(self, data, x0):
        n, m, dt = self.A.num_rows, self.m, x0.dtype
        k = self.wanted_count
        v0 = x0 / jnp.maximum(jnp.linalg.norm(x0), 1e-30)
        # X holds the k kept Ritz vectors; initially random orthonormal
        # with x0 as the first column
        rng = np.random.default_rng(3)
        X0 = jnp.asarray(rng.standard_normal((n, k)), dt)
        X0 = X0.at[:, 0].set(v0)
        X0, _ = jnp.linalg.qr(X0)
        return {
            "X": X0,                       # (n, k) kept Ritz block
            # expansion seed: independent random direction (NOT in
            # span(X) — the chain would degenerate)
            "q": jnp.asarray(rng.standard_normal(n), dt),
            "lambdas": jnp.zeros((k,), dt),
            "resid": jnp.full((k,), jnp.inf, dt),
        }

    def solve_iteration(self, data, state):
        m, k = self.m, self.wanted_count
        dt = state["X"].dtype
        n = self.A.num_rows
        # basis buffer: rows 0..k-1 = kept Ritz block, row k = seed
        V = jnp.zeros((m, n), dt)
        V = V.at[:k].set(state["X"].T)

        def _orth_unit(w, Vm, j):
            """Orthogonalize w against Vm's active rows; on breakdown
            (w in span) fall back to a deterministic fresh direction."""
            for _ in range(2):
                w = w - Vm.T @ (Vm @ w)
            wn = jnp.linalg.norm(w)
            fb = jnp.sin((jnp.asarray(j, dt) + 2.0)
                         * jnp.arange(n, dtype=dt) + 0.7)
            w = jnp.where(wn > 1e-10, w, fb)
            for _ in range(2):
                w = w - Vm.T @ (Vm @ w)
            return w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

        q = _orth_unit(state["q"], state["X"].T, 0)
        V = V.at[k].set(q)

        def step(j, Vb):
            w = self.op.apply(data["op"], Vb[j])
            mask = (jnp.arange(m) <= j)[:, None].astype(dt)
            Vm = Vb * mask
            return Vb.at[j + 1].set(_orth_unit(w, Vm, j))

        V = jax.lax.fori_loop(k, m - 1, step, V)
        AV = jax.vmap(lambda row: self.op.apply(data["op"], row))(V)
        G = V @ AV.T
        G = 0.5 * (G + G.T)
        lam, S = jnp.linalg.eigh(G)           # ascending
        if self.which == "smallest":
            idx = jnp.arange(k)
        else:
            idx = jnp.arange(m - 1, m - 1 - k, -1)
        lam_k, S_k = lam[idx], S[:, idx]
        X = V.T @ S_k                          # (n, k) Ritz vectors
        AX = AV.T @ S_k
        R = AX - X * lam_k[None, :]
        resid = jnp.linalg.norm(R, axis=0)
        # reseed from the least-converged pair so every wanted pair keeps
        # receiving Krylov directions
        q_next = R[:, jnp.argmax(resid)]
        return {"X": X, "q": q_next, "lambdas": lam_k, "resid": resid}

    def finalize(self, data, state):
        vec = state["X"] if self.want_vectors else None
        return state["lambdas"], vec, state["resid"]


@registry.eigensolvers.register("ARNOLDI")
class ArnoldiEigenSolver(EigenSolver):
    """Arnoldi for general (nonsymmetric) matrices
    (arnoldi_eigensolver.cu). The jitted device program builds V and H in
    one m-step factorization; the host solves the Hessenberg
    eigenproblem (LAPACK-geev analog)."""

    def solver_setup(self):
        self.m = _krylov_dim(self)

    def _factorize(self, data, x0):
        n, m, dt = self.A.num_rows, self.m, x0.dtype
        v0 = x0 / jnp.maximum(jnp.linalg.norm(x0), 1e-30)
        V0 = jnp.zeros((m + 1, n), dt).at[0].set(v0)
        H0 = jnp.zeros((m + 1, m), dt)

        def step(j, st):
            V, H = st
            w = self.op.apply(data["op"], V[j])
            mask = (jnp.arange(m + 1) <= j)[:, None].astype(dt)
            Vm = V * mask
            h = Vm @ w
            w = w - Vm.T @ h
            h2 = Vm @ w
            w = w - Vm.T @ h2
            h = h + h2
            b = jnp.linalg.norm(w)
            w = w / jnp.maximum(b, 1e-30)
            H = H.at[:, j].set(h).at[j + 1, j].set(b)
            V = V.at[j + 1].set(w)
            return (V, H)

        return jax.lax.fori_loop(0, m, step, (V0, H0))

    def solve(self, x0=None) -> EigenResult:
        if self.A is None:
            from ..errors import BadParametersError
            raise BadParametersError("ARNOLDI: solve() before setup()")
        n = self.A.num_rows
        if x0 is None:
            x0 = np.random.default_rng(42).standard_normal(n)
        x0 = jnp.asarray(x0, dtype=self.A.dtype)
        key = (x0.shape, str(x0.dtype))
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(self._factorize)
        t0 = time.perf_counter()
        V, H = self._jit_cache[key](self.solve_data(), x0)
        jax.block_until_ready(V)
        solve_time = time.perf_counter() - t0
        m, k = self.m, self.wanted_count
        V, H = np.asarray(V), np.asarray(H)
        w, S = np.linalg.eig(H[:m, :m])
        order = np.argsort(w.real)
        idx = order[:k] if self.which == "smallest" else order[-k:][::-1]
        lam_k, S_k = w[idx], S[:, idx]
        res = np.abs(H[m, m - 1]) * np.abs(S_k[m - 1, :])
        vec = None
        if self.want_vectors:
            X = V[:m].T @ S_k.real
            vec = X / np.maximum(np.linalg.norm(X, axis=0), 1e-30)
        if np.allclose(lam_k.imag, 0):
            lam_k = lam_k.real
        scale = max(float(np.max(np.abs(lam_k))), 1e-30)
        return EigenResult(
            eigenvalues=np.atleast_1d(self.unshift(lam_k)),
            eigenvectors=vec, iterations=m,
            converged=bool(np.all(res <= self.tolerance * scale)),
            residuals=np.atleast_1d(res),
            setup_time=self.setup_time, solve_time=solve_time)
