"""EigenSolver base: the eigensolver skeleton.

TPU-native analog of EigenSolver<TConfig>
(include/eigensolvers/eigensolver.h:25, src/eigensolvers/eigensolver.cu):
reads the eig_* parameter family, applies the spectral shift, runs a
jitted iteration loop with traced convergence checks, and postprocesses
(un-shift, optional eigenvector extraction).

Execution model mirrors solvers/base.py: `setup(A)` is host-orchestrated
once per structure; `solve()` compiles one XLA program — a
`lax.while_loop` whose body is `solve_iteration` — with no host
round-trips inside the loop. Small dense eigenproblems (tridiagonal T,
Hessenberg H, Rayleigh-Ritz Gram matrices) use `jnp.linalg.eigh` in-trace
for symmetric cases; the nonsymmetric Hessenberg eigenproblem is solved
on the host after the device loop (the reference likewise defers it to
LAPACK geev, src/amgx_lapack.cu).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import registry
from ..config import Config
from ..errors import BadParametersError
from ..matrix import CsrMatrix
from .operators import MatrixOperator, Operator, ShiftedOperator


@dataclasses.dataclass
class EigenResult:
    """Result of an eigensolve (AMGX_eigensolver_solve analog)."""
    eigenvalues: np.ndarray            # (k,)
    eigenvectors: Optional[np.ndarray]  # (n, k) or None
    iterations: int
    converged: bool
    residuals: np.ndarray              # (k,) final eigenpair residuals
    setup_time: float = 0.0
    solve_time: float = 0.0


class EigenSolver:
    """Base eigensolver (include/eigensolvers/eigensolver.h:25).

    Subclasses implement `solver_setup`, `solve_init`, `solve_iteration`,
    `finalize`; the base provides the shift, the jitted driver, and the
    convergence plumbing."""

    def __init__(self, cfg: Config, scope: str = "default", name: str = "?"):
        self.cfg = cfg
        self.scope = scope
        self.name = name
        self.max_iters = int(cfg.get("eig_max_iters", scope))
        self.tolerance = float(cfg.get("eig_tolerance", scope))
        self.shift = float(cfg.get("eig_shift", scope))
        self.which = str(cfg.get("eig_which", scope)).lower()
        self.wanted_count = int(cfg.get("eig_wanted_count", scope))
        self.subspace_size = int(cfg.get("eig_subspace_size", scope))
        self.check_freq = max(1, int(cfg.get("eig_convergence_check_freq",
                                             scope)))
        self.want_vectors = bool(int(cfg.get("eig_eigenvector", scope)))
        self.damping = float(cfg.get("eig_damping_factor", scope))
        self.A: Optional[CsrMatrix] = None
        self.op: Optional[Operator] = None
        self.setup_time = 0.0
        self._jit_cache: Dict[Any, Any] = {}

    # -- setup -----------------------------------------------------------
    def make_operator(self) -> Operator:
        """The operator the iteration applies. Default: (A - shift I)."""
        op: Operator = MatrixOperator(self.A)
        if self.shift != 0.0:
            op = ShiftedOperator(op, self.shift)
        return op

    def setup(self, A: CsrMatrix):
        t0 = time.perf_counter()
        if not A.initialized:
            A = A.init()
        if A.block_size != 1:
            raise BadParametersError(
                f"eigensolver {self.name}: block matrices not supported")
        self.A = A
        self.op = self.make_operator()
        self.solver_setup()
        self._jit_cache.clear()
        self.setup_time = time.perf_counter() - t0
        return self

    def solver_setup(self):
        pass

    # -- pure pieces -----------------------------------------------------
    def solve_data(self) -> Dict[str, Any]:
        return {"op": self.op.data()}

    def solve_init(self, data, x0) -> Dict[str, Any]:
        """Initial state. Must contain 'lambdas' (k,) and 'resid' (k,)."""
        raise NotImplementedError

    def solve_iteration(self, data, state) -> Dict[str, Any]:
        raise NotImplementedError

    def finalize(self, data, state):
        """Return (lambdas (k,), vectors (n,k) or None, resid (k,))."""
        raise NotImplementedError

    def unshift(self, lam):
        return lam + self.shift if self.shift != 0.0 else lam

    # -- driver ----------------------------------------------------------
    def _build_solve_fn(self):
        max_iters = self.max_iters
        tol = self.tolerance
        freq = self.check_freq

        def solve_fn(data, x0):
            state = self.solve_init(data, x0)
            state["iters"] = jnp.asarray(0, jnp.int32)
            state["done"] = jnp.asarray(False)

            def cond(st):
                return (~st["done"]) & (st["iters"] < max_iters)

            def body(st):
                iters = st["iters"]
                core = {k: v for k, v in st.items()
                        if k not in ("iters", "done")}
                core = self.solve_iteration(data, core)
                new = dict(core)
                new["iters"] = iters + 1
                scale = jnp.maximum(jnp.max(jnp.abs(core["lambdas"])), 1e-30)
                conv = jnp.all(core["resid"] <= tol * scale)
                new["done"] = conv & (((iters + 1) % freq) == 0)
                return new

            final = jax.lax.while_loop(cond, body, state)
            lam, vec, resid = self.finalize(data, final)
            scale = jnp.maximum(jnp.max(jnp.abs(lam)), 1e-30)
            conv = jnp.all(resid <= tol * scale)
            # pack scalars/small stats into ONE auxiliary output:
            # remote/tunneled rigs pay a round trip per awaited buffer
            # (see solvers/base.py)
            rdt = jnp.promote_types(jnp.asarray(lam).dtype, jnp.float32)
            if jnp.issubdtype(rdt, jnp.complexfloating):
                rdt = jnp.float64
                lam_flat = jnp.concatenate([jnp.real(lam), jnp.imag(lam)])
                complex_lam = True
            else:
                lam_flat = jnp.ravel(lam)
                complex_lam = False
            stats = jnp.concatenate([
                jnp.reshape(final["iters"].astype(rdt), (1,)),
                jnp.reshape(conv.astype(rdt), (1,)),
                lam_flat.astype(rdt), jnp.ravel(resid).astype(rdt)])
            if vec is None:
                vec = jnp.zeros((0,), stats.dtype)
            self._complex_lam = complex_lam
            return vec, stats

        return solve_fn

    def solve(self, x0=None) -> EigenResult:
        if self.A is None:
            raise BadParametersError(
                f"eigensolver {self.name}: solve() before setup()")
        n = self.A.num_rows
        if x0 is None:
            # deterministic pseudo-random start (reference seeds its RNG)
            x0 = jnp.asarray(
                np.random.default_rng(42).standard_normal(n),
                dtype=self.A.dtype)
        else:
            x0 = jnp.asarray(x0, dtype=self.A.dtype)
        key = (x0.shape, str(x0.dtype))
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(self._build_solve_fn())
        t0 = time.perf_counter()
        vec, stats = jax.block_until_ready(self._jit_cache[key](
            self.solve_data(), x0))
        solve_time = time.perf_counter() - t0
        stats = np.asarray(stats)                   # one host fetch
        iters = int(stats[0])
        conv = bool(stats[1])
        body = stats[2:]
        if getattr(self, "_complex_lam", False):
            m = body.size // 3
            lam = body[:m] + 1j * body[m:2 * m]
            resid = body[2 * m:]
        else:
            m = body.size // 2
            lam, resid = body[:m], body[m:]
        if vec.size == 0:
            vec = None
        lam, vec, resid, iters, conv = self.postprocess(
            lam, vec, resid, iters, conv)
        return EigenResult(
            eigenvalues=np.atleast_1d(np.asarray(self.unshift(lam))),
            eigenvectors=None if vec is None else np.asarray(vec),
            iterations=int(iters), converged=bool(conv),
            residuals=np.atleast_1d(np.asarray(resid)),
            setup_time=self.setup_time, solve_time=solve_time)

    def postprocess(self, lam, vec, resid, iters, conv):
        """Host-side post-loop hook (Arnoldi solves its Hessenberg
        eigenproblem here, the way the reference calls LAPACK)."""
        return lam, vec, resid, iters, conv


def make_eigensolver(name: str, cfg: Config, scope: str = "default"
                     ) -> EigenSolver:
    """EigenSolverFactory::allocate analog."""
    cls = registry.eigensolvers.get(name)
    return cls(cfg, scope, name=name.upper())


def create_eigensolver(cfg: Config, scope: str = "default") -> EigenSolver:
    """AMG_EigenSolver analog (src/amg_eigensolver.cu): build the
    eigensolver named by eig_solver."""
    return make_eigensolver(str(cfg.get("eig_solver", scope)), cfg, scope)
