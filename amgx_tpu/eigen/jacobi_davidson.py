"""Jacobi-Davidson eigensolver.

TPU-native analog of src/eigensolvers/jacobi_davidson_eigensolver.cu.
Single-pair JD: a growing search subspace V, expanded each iteration by
an approximate solution t of the correction equation

    (I - u u^T)(A - theta I)(I - u u^T) t = -r,   t  ⊥  u

solved with a fixed number of (unpreconditioned) CG steps — the analog
of the reference's inner solver. XLA needs static shapes, so V lives in
a fixed (m_max, n) buffer with a column-count mask; when full, the
subspace restarts from the current Ritz vector. The whole outer loop is
one jitted while_loop: the projected eigenproblem is an m_max x m_max
masked `eigh` (unused rows pinned far from the wanted end of the
spectrum so they are never selected).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import registry
from .base import EigenSolver

_INNER_CG_STEPS = 8
_PIN = 1e30


@registry.eigensolvers.register("JACOBI_DAVIDSON")
class JacobiDavidsonEigenSolver(EigenSolver):

    def solver_setup(self):
        from ..errors import BadParametersError
        if self.wanted_count > 1:
            raise BadParametersError(
                "JACOBI_DAVIDSON computes one eigenpair; use LANCZOS or "
                "LOBPCG for eig_wanted_count > 1")
        m = self.subspace_size
        self.m_max = min(m if m > 0 else 12, self.A.num_rows)

    # -- pieces ----------------------------------------------------------
    def _proj_op(self, data, u, theta, t):
        """(I - uu^T)(A - theta I)(I - uu^T) t."""
        t = t - u * jnp.dot(u, t)
        y = self.op.apply(data["op"], t) - theta * t
        return y - u * jnp.dot(u, y)

    def _correction(self, data, u, theta, r):
        """Approximate JD correction by fixed CG steps (inner solver)."""
        b = -(r - u * jnp.dot(u, r))
        t0 = jnp.zeros_like(b)

        def body(_, st):
            t, p, res, rs = st
            Ap = self._proj_op(data, u, theta, p)
            denom = jnp.dot(p, Ap)
            alpha = jnp.where(jnp.abs(denom) > 1e-30, rs / denom, 0.0)
            t = t + alpha * p
            res_n = res - alpha * Ap
            rs_n = jnp.dot(res_n, res_n)
            beta = jnp.where(rs > 1e-30, rs_n / rs, 0.0)
            p = res_n + beta * p
            return (t, p, res_n, rs_n)

        st = (t0, b, b, jnp.dot(b, b))
        t, *_ = jax.lax.fori_loop(0, _INNER_CG_STEPS, body, st)
        # fall back to steepest descent direction if CG broke down
        bad = jnp.linalg.norm(t) < 1e-14
        return jnp.where(bad, b, t)

    # -- driver pieces ---------------------------------------------------
    def solve_init(self, data, x0):
        n, m, dt = self.A.num_rows, self.m_max, x0.dtype
        v0 = x0 / jnp.maximum(jnp.linalg.norm(x0), 1e-30)
        V = jnp.zeros((m, n), dt).at[0].set(v0)
        return {"V": V, "count": jnp.asarray(1, jnp.int32),
                "u": v0,
                "lambdas": jnp.asarray([jnp.dot(
                    v0, self.op.apply(data["op"], v0))], dt),
                "resid": jnp.full((1,), jnp.inf, dt)}

    def solve_iteration(self, data, state):
        m = self.m_max
        V, j = state["V"], state["count"]
        dt = V.dtype
        mask = (jnp.arange(m) < j).astype(dt)
        Vm = V * mask[:, None]
        AV = jax.vmap(lambda row: self.op.apply(data["op"], row))(Vm)
        G = Vm @ AV.T
        G = 0.5 * (G + G.T)
        # pin unused rows away from the wanted end of the spectrum
        pin = -_PIN if self.which != "smallest" else _PIN
        G = G + jnp.diag((1.0 - mask) * pin)
        lam, W = jnp.linalg.eigh(G)
        sel = m - 1 if self.which != "smallest" else 0
        theta, w = lam[sel], W[:, sel]
        u = Vm.T @ w
        u = u / jnp.maximum(jnp.linalg.norm(u), 1e-30)
        Au = self.op.apply(data["op"], u)
        theta = jnp.dot(u, Au)
        r = Au - theta * u
        resid = jnp.linalg.norm(r)
        t = self._correction(data, u, theta, r)
        # orthogonalize t against the active columns (CGS x2)
        for _ in range(2):
            t = t - Vm.T @ (Vm @ t)
        tn = jnp.linalg.norm(t)
        t = t / jnp.maximum(tn, 1e-30)
        # append (j < m) or restart from the Ritz vector (j == m)
        full = j >= m
        V_app = V.at[jnp.minimum(j, m - 1)].set(t)
        V_res = jnp.zeros_like(V).at[0].set(u)
        V_new = jnp.where(full, V_res, V_app)
        j_new = jnp.where(full, jnp.asarray(1, jnp.int32), j + 1)
        return {"V": V_new, "count": j_new, "u": u,
                "lambdas": theta[None], "resid": resid[None]}

    def finalize(self, data, state):
        vec = state["u"][:, None] if self.want_vectors else None
        return state["lambdas"], vec, state["resid"]
