"""IDR(s) induced-dimension-reduction Krylov solvers.

Analogs of src/solvers/idr_solver.cu (586 LoC) and idrmsync_solver.cu
(686 LoC). The algorithm is the biorthogonal IDR(s) of van Gijzen &
Sonneveld (ACM TOMS 38(1), 2011 — public); the shadow space dimension is
`subspace_dim_s`.

One `solve_iteration` here performs a full IDR cycle (s intermediate
steps + the dimension-reduction step = s+1 SpMVs), with the per-step
inner products expressed as batched (n,s) matrix contractions. That
batching is exactly the "minimized synchronization" reformulation
idrmsync exists for on GPUs — under XLA a whole cycle compiles into one
program and the compiler schedules the reductions, so both registered
names run this formulation; iteration counts match the biortho IDR(s)
recurrence either way.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import registry
from ..ops import blas
from .base import Solver
from .krylov import _KrylovBase, _safe_div
from ..ops.spmv import spmv


@registry.solvers.register("IDR")
@registry.solvers.register("IDRMSYNC")
class IDRSolver(_KrylovBase):
    """IDR(s) with biorthogonalization of the shadow residuals."""

    uses_preconditioner = True

    def __init__(self, cfg, scope="default", name="IDR"):
        super().__init__(cfg, scope, name)
        self.s = max(int(cfg.get("subspace_dim_s", scope)), 1)
        self.kappa = 0.7          # omega angle correction (standard)

    def solver_setup(self):
        n = self.A.num_rows * self.A.block_dimx
        s = self.s
        # fixed-seed shadow space: deterministic runs (determinism_flag
        # semantics); orthonormalized columns
        P = np.random.default_rng(271828).standard_normal((n, s))
        P, _ = np.linalg.qr(P)
        self._P = jnp.asarray(P, dtype=self.A.dtype)

    def solve_data(self):
        d = super().solve_data()
        d["P"] = self._P
        return d

    def solve_init(self, data, b, x, r):
        n, s = r.shape[0], self.s
        dt = r.dtype
        return {
            "G": jnp.zeros((n, s), dt), "U": jnp.zeros((n, s), dt),
            "M": jnp.eye(s, dtype=dt), "omega": jnp.ones((), dt),
            **self._guard_init(),
        }

    def solve_iteration(self, data, b, st):
        A, P = data["A"], data["P"]
        s = self.s
        x, r = st["x"], st["r"]
        G, U, M, omega = st["G"], st["U"], st["M"], st["omega"]
        f = P.T @ r                                   # (s,)
        for k in range(s):
            # solve M[k:,k:] c = f[k:]  (lower triangular, small static
            # s); a zero pivot is a shadow-space breakdown — guard it to
            # keep NaN out of x (the _safe_div convention of krylov.py)
            dM = jnp.diagonal(M)
            M_safe = M + jnp.diag((dM == 0).astype(M.dtype))
            c = jax.scipy.linalg.solve_triangular(M_safe[k:, k:], f[k:],
                                                  lower=True)
            v = r - G[:, k:] @ c
            v = self._precond(data, v)
            u_k = omega * v + U[:, k:] @ c
            g_k = spmv(A, u_k)
            # biorthogonalize g_k against P[:, :k]
            if k > 0:
                dMk = jnp.diagonal(M)[:k]
                alpha = (P[:, :k].T @ g_k) / jnp.where(dMk == 0, 1.0, dMk) \
                    * (dMk != 0)
                g_k = g_k - G[:, :k] @ alpha
                u_k = u_k - U[:, :k] @ alpha
            G = G.at[:, k].set(g_k)
            U = U.at[:, k].set(u_k)
            # new column k of M
            Mk = P.T @ g_k                            # (s,)
            M = M.at[:, k].set(Mk)
            beta = _safe_div(f[k], M[k, k])
            r = r - beta * g_k
            x = x + beta * u_k
            if k + 1 < s:
                f = f.at[k + 1:].add(-beta * M[k + 1:, k])
                f = f.at[:k + 1].set(0.0)
        # dimension-reduction step
        v = self._precond(data, r)
        t = spmv(A, v)
        tt = blas.dot(t, t)
        tr = blas.dot(t, r)
        om = _safe_div(tr, tt)
        # angle correction: keep |cos| >= kappa for robustness
        nr, nt = blas.nrm2(r), jnp.sqrt(jnp.where(tt == 0, 1.0, tt))
        rho = jnp.abs(_safe_div(tr, nt * jnp.where(nr == 0, 1.0, nr)))
        om = jnp.where(rho < self.kappa,
                       om * _safe_div(jnp.asarray(self.kappa, om.dtype), rho),
                       om)
        x = x + om * v
        r = r - om * t
        out = {**st, "x": x, "r": r, "G": G, "U": U, "M": M, "omega": om}
        if self.health_guards:
            # omega collapse: the dimension-reduction step degenerated
            # (t == 0 or t orthogonal to r) — IDR(s) cannot proceed
            out["breakdown"] = om == 0
        return out
