"""Solver layer: registers all solver classes on import
(registerClasses analog, src/core.cu:596-625)."""
from . import base  # noqa: F401  (convergence criteria)
from . import relaxation  # noqa: F401
from . import direct  # noqa: F401
from . import krylov  # noqa: F401
from . import gmres  # noqa: F401
from . import multicolor  # noqa: F401
from . import idr  # noqa: F401
from . import polynomial  # noqa: F401
from . import kaczmarz  # noqa: F401
from . import refinement  # noqa: F401

from .base import Solver, SolveResult, make_solver  # noqa: F401
