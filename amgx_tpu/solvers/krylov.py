"""Krylov solvers: CG / PCG / PCGF / BiCGStab / PBiCGStab / Chebyshev.

Analogs of src/solvers/cg_solver.cu, pcg_solver.cu, pcgf_solver.cu,
bicgstab_solver.cu, pbicgstab_solver.cu, cheb_solver.cu. Each iteration
is a pure function over a dict state; the base driver compiles the whole
iteration loop (SpMV + reductions + preconditioner application) into one
XLA program, so dot products stay on device and distributed runs finish
reductions with psum instead of MPI_Allreduce.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import registry
from ..ops import blas
from ..ops.spmv import spmv, spmv_pdot, spmv_ddot
from .base import Solver


def _safe_div(a, b):
    return a / jnp.where(b == 0, 1.0, b) * (b != 0)


def _ldot(a, b):
    """LOCAL dot in f32+ accumulation (the epilogue dtype of the fused
    shell kernels); fused iterations finish their LOCAL scalars with
    ONE packed collective (blas.psum_bundle) instead of per-dot psums."""
    cdt = jnp.promote_types(a.dtype, jnp.float32)
    return jnp.vdot(a.astype(cdt), b.astype(cdt))


class _KrylovBase(Solver):
    def __init__(self, cfg, scope="default", name="?"):
        super().__init__(cfg, scope, name)
        # Krylov shell fusion (ops/spmv.spmv_pdot / blas.cg_update /
        # the preconditioner's cycle-borne r.z): 0 restores the
        # unfused SpMV + BLAS-1 composition bit-for-bit
        self.krylov_fusion = bool(int(cfg.get("krylov_fusion", scope)))

    def _precond(self, data, r):
        if self.preconditioner is not None:
            return self.preconditioner.apply(data["precond"], r)
        return r

    def _precond_dot(self, data, r):
        """(z, LOCAL r.z): the dot rides the preconditioner
        application's last kernel when it can (AMG cycle_dot — the
        cycle's output IS z and its rhs IS r), the explicit local
        reduction otherwise; identity preconditioner gives (r, r.r)."""
        if self.preconditioner is None:
            return r, _ldot(r, r)
        z, d = self.preconditioner.apply_dot(data["precond"], r)
        if d is None:
            d = _ldot(r, z)
        return z, d

    def _l2_scalar_norm(self) -> bool:
        """True when the driver's monitored norm is the plain scalar L2
        — the only shape a solver-maintained r.r scalar can stand in
        for (internal_res_norm)."""
        if self.norm_type.upper() != "L2":
            return False
        bs = self.A.block_dimx if self.A is not None else 1
        return bs <= 1 or self.use_scalar_norm


@registry.solvers.register("CG")
class CGSolver(_KrylovBase):
    """Unpreconditioned conjugate gradients (cg_solver.cu)."""

    def solve_init(self, data, b, x, r):
        if self.krylov_fusion:
            # fused state seeds the direction-update PROLOGUE: the
            # first iteration's p' = z + beta p with z=r, beta=0, p=0
            # reproduces the unfused p0 = r inside the SpMV kernel
            (rz,) = blas.psum_bundle((_ldot(r, r),))
            return {"p": jnp.zeros_like(r),
                    "beta": jnp.zeros((), rz.dtype), "rz": rz,
                    **self._guard_init()}
        return {"p": r, "rz": blas.dot(r, r), **self._guard_init()}

    def solve_iteration(self, data, b, st):
        if self.krylov_fusion:
            return self._fused_iteration(data, st)
        A = data["A"]
        x, r, p, rz = st["x"], st["r"], st["p"], st["rz"]
        Ap = spmv(A, p)
        pAp = blas.dot(p, Ap)
        alpha = _safe_div(rz, pAp)
        x = x + alpha * p
        r = r - alpha * Ap
        rz_new = blas.dot(r, r)
        beta = _safe_div(rz_new, rz)
        p = r + beta * p
        out = {**st, "x": x, "r": r, "p": p, "rz": rz_new}
        if self.health_guards:
            # p.Ap <= 0: the matrix is not SPD on this Krylov space —
            # a CG breakdown (p == 0 from exact convergence also lands
            # here, but the CONVERGED check wins in the driver)
            out["breakdown"] = pAp <= 0
        return out

    def _fused_iteration(self, data, st):
        """Two single-pass kernels per iteration: (p', Ap', p'.Ap')
        with the direction update folded in as a prologue, then
        (x', r', r'.r') — every n-vector is read once per kernel and
        the iteration's scalars psum in at most two packed bundles."""
        A = data["A"]
        x, r, rz = st["x"], st["r"], st["rz"]
        p, Ap, pAp = spmv_pdot(A, st["p"], r, st["beta"])
        (pAp,) = blas.psum_bundle((pAp,))
        alpha = _safe_div(rz, pAp)
        x, r, rr = blas.cg_update(x, p, r, Ap, alpha)
        (rz_new,) = blas.psum_bundle((rr,))
        beta = _safe_div(rz_new, rz)
        out = {**st, "x": x, "r": r, "p": p, "rz": rz_new,
               "beta": beta}
        if self.health_guards:
            out["breakdown"] = pAp <= 0
        return out

    def internal_res_norm(self, state):
        # CG's rz IS r.r — the monitored scalar L2 norm squared — on
        # BOTH routes, so the driver's standalone blas.norm(r)
        # full-vector pass is dead code under the monitor
        if not self._l2_scalar_norm():
            return None
        return jnp.sqrt(state["rz"])


@registry.solvers.register("PCG")
class PCGSolver(_KrylovBase):
    """Preconditioned CG (pcg_solver.cu)."""

    uses_preconditioner = True

    def solve_init(self, data, b, x, r):
        if self.krylov_fusion:
            z, rz_l = self._precond_dot(data, r)
            rr, rz = blas.psum_bundle((_ldot(r, r), rz_l))
            return {"p": jnp.zeros_like(r), "z": z,
                    "beta": jnp.zeros((), rz.dtype), "rz": rz,
                    "rr": rr, **self._guard_init()}
        z = self._precond(data, r)
        return {"p": z, "z": z, "rz": blas.dot(r, z),
                **self._guard_init()}

    def solve_iteration(self, data, b, st):
        if self.krylov_fusion:
            return self._fused_iteration(data, st)
        A = data["A"]
        x, r, p, rz = st["x"], st["r"], st["p"], st["rz"]
        Ap = spmv(A, p)
        pAp = blas.dot(p, Ap)
        alpha = _safe_div(rz, pAp)
        x = x + alpha * p
        r = r - alpha * Ap
        z = self._precond(data, r)
        rz_new = blas.dot(r, z)
        beta = _safe_div(rz_new, rz)
        p = z + beta * p
        out = {**st, "x": x, "r": r, "p": p, "z": z, "rz": rz_new}
        if self.health_guards:
            out["breakdown"] = pAp <= 0
        return out

    def _fused_iteration(self, data, st):
        """Fused-hierarchy PCG iteration: the p-update+SpMV+p.Ap
        kernel, the x/r-update+r.r kernel, and r.z riding the
        preconditioner cycle's last kernel — zero standalone
        full-vector reductions, and the post-alpha scalars (r.r, r.z)
        share ONE packed psum."""
        A = data["A"]
        x, r, rz = st["x"], st["r"], st["rz"]
        p, Ap, pAp = spmv_pdot(A, st["p"], st["z"], st["beta"])
        (pAp,) = blas.psum_bundle((pAp,))
        alpha = _safe_div(rz, pAp)
        x, r, rr = blas.cg_update(x, p, r, Ap, alpha)
        z, rz_l = self._precond_dot(data, r)
        rr, rz_new = blas.psum_bundle((rr, rz_l))
        beta = _safe_div(rz_new, rz)
        out = {**st, "x": x, "r": r, "p": p, "z": z, "rz": rz_new,
               "rr": rr, "beta": beta}
        if self.health_guards:
            out["breakdown"] = pAp <= 0
        return out

    def internal_res_norm(self, state):
        # the fused route's r.r exits the x/r-update kernel's epilogue
        # — the monitor's norm costs zero extra passes
        if "rr" not in state or not self._l2_scalar_norm():
            return None
        return jnp.sqrt(state["rr"])


@registry.solvers.register("PCGF")
class PCGFSolver(_KrylovBase):
    """Flexible PCG (pcgf_solver.cu): Polak-Ribiere beta so the
    preconditioner may vary between iterations."""

    uses_preconditioner = True

    def solve_init(self, data, b, x, r):
        if self.krylov_fusion:
            z, rz_l = self._precond_dot(data, r)
            rr, rz = blas.psum_bundle((_ldot(r, r), rz_l))
            return {"p": jnp.zeros_like(r), "z": z,
                    "beta": jnp.zeros((), rz.dtype), "rz": rz,
                    "rr": rr, **self._guard_init()}
        z = self._precond(data, r)
        return {"p": z, "z": z, "r_old": r, "rz": blas.dot(r, z),
                **self._guard_init()}

    def solve_iteration(self, data, b, st):
        if self.krylov_fusion:
            return self._fused_iteration(data, st)
        A = data["A"]
        x, r, p, rz = st["x"], st["r"], st["p"], st["rz"]
        Ap = spmv(A, p)
        pAp = blas.dot(p, Ap)
        alpha = _safe_div(rz, pAp)
        x = x + alpha * p
        r_new = r - alpha * Ap
        z = self._precond(data, r_new)
        # flexible beta: <z, r_new - r> / <r, z_old-ish rz>
        rz_new = blas.dot(r_new, z)
        beta = _safe_div(blas.dot(r_new - r, z), rz)
        p = z + beta * p
        out = {**st, "x": x, "r": r_new, "p": p, "z": z, "r_old": r,
               "rz": rz_new}
        if self.health_guards:
            out["breakdown"] = pAp <= 0
        return out

    def _fused_iteration(self, data, st):
        """Fused flexible PCG: same two shell kernels + cycle-borne
        r.z as PCG; the Polak-Ribiere numerator <z, r_new - r> is the
        one reduction the kernels cannot absorb (it needs the OLD r
        after the new one exists) and packs into the same psum bundle."""
        A = data["A"]
        x, r, rz = st["x"], st["r"], st["rz"]
        p, Ap, pAp = spmv_pdot(A, st["p"], st["z"], st["beta"])
        (pAp,) = blas.psum_bundle((pAp,))
        alpha = _safe_div(rz, pAp)
        x, r_new, rr = blas.cg_update(x, p, r, Ap, alpha)
        z, rz_l = self._precond_dot(data, r_new)
        dz_l = _ldot(r_new - r, z)
        rr, rz_new, dz = blas.psum_bundle((rr, rz_l, dz_l))
        beta = _safe_div(dz, rz)
        out = {**st, "x": x, "r": r_new, "p": p, "z": z, "rz": rz_new,
               "rr": rr, "beta": beta}
        if self.health_guards:
            out["breakdown"] = pAp <= 0
        return out

    def internal_res_norm(self, state):
        if "rr" not in state or not self._l2_scalar_norm():
            return None
        return jnp.sqrt(state["rr"])


@registry.solvers.register("BICGSTAB")
class BiCGStabSolver(_KrylovBase):
    """BiCGStab (bicgstab_solver.cu)."""

    def solve_init(self, data, b, x, r):
        if self.krylov_fusion:
            (rho,) = blas.psum_bundle((_ldot(r, r),))
            one = jnp.ones((), rho.dtype)
        else:
            rho = blas.dot(r, r)
            one = jnp.ones((), r.dtype)
        return {"r_tld": r, "p": r, "v": jnp.zeros_like(r),
                "rho": rho, "alpha": one, "omega": one,
                **self._guard_init()}

    def solve_iteration(self, data, b, st):
        if self.krylov_fusion:
            return self._fused_iteration(data, st)
        A = data["A"]
        x, r = st["x"], st["r"]
        r_tld, p, rho = st["r_tld"], st["p"], st["rho"]
        v = spmv(A, p)
        alpha = _safe_div(rho, blas.dot(r_tld, v))
        s = r - alpha * v
        t = spmv(A, s)
        omega = _safe_div(blas.dot(t, s), blas.dot(t, t))
        x = x + alpha * p + omega * s
        r = s - omega * t
        rho_new = blas.dot(r_tld, r)
        beta = _safe_div(rho_new * alpha, rho * omega)
        p = r + beta * (p - omega * v)
        out = {**st, "x": x, "r": r, "p": p, "v": v, "rho": rho_new,
               "alpha": alpha, "omega": omega}
        if self.health_guards:
            # rho underflow (shadow residual orthogonal to r) or omega
            # collapse: the BiCGStab recurrence is dead — exit cleanly
            out["breakdown"] = (rho_new == 0) | (omega == 0)
        return out

    def _fused_iteration(self, data, st):
        """Both SpMVs carry their dots as kernel epilogues: r_tld.v
        with v = A p, and the t.s / t.t PAIR with t = A s (self_dot)
        — four standalone full-vector reductions become two epilogue
        reads plus the one rho dot the kernels cannot see."""
        A = data["A"]
        x, r = st["x"], st["r"]
        r_tld, p, rho = st["r_tld"], st["p"], st["rho"]
        v, rtv = spmv_ddot(A, p, r_tld)
        (rtv,) = blas.psum_bundle((rtv,))
        alpha = _safe_div(rho, rtv)
        s = r - alpha.astype(r.dtype) * v
        t, ts, tt = spmv_ddot(A, s, s, self_dot=True)
        ts, tt = blas.psum_bundle((ts, tt))
        omega = _safe_div(ts, tt)
        w = omega.astype(r.dtype)
        x = x + alpha.astype(r.dtype) * p + w * s
        r = s - w * t
        (rho_new,) = blas.psum_bundle((_ldot(r_tld, r),))
        beta = _safe_div(rho_new * alpha, rho * omega)
        p = r + beta.astype(r.dtype) * (p - w * v)
        out = {**st, "x": x, "r": r, "p": p, "v": v, "rho": rho_new,
               "alpha": alpha, "omega": omega}
        if self.health_guards:
            out["breakdown"] = (rho_new == 0) | (omega == 0)
        return out


@registry.solvers.register("PBICGSTAB")
class PBiCGStabSolver(_KrylovBase):
    """Preconditioned BiCGStab (pbicgstab_solver.cu)."""

    uses_preconditioner = True

    def solve_init(self, data, b, x, r):
        if self.krylov_fusion:
            (rho,) = blas.psum_bundle((_ldot(r, r),))
            one = jnp.ones((), rho.dtype)
        else:
            rho = blas.dot(r, r)
            one = jnp.ones((), r.dtype)
        return {"r_tld": r, "p": r, "v": jnp.zeros_like(r),
                "rho": rho, "alpha": one, "omega": one,
                **self._guard_init()}

    def solve_iteration(self, data, b, st):
        if self.krylov_fusion:
            return self._fused_iteration(data, st)
        A = data["A"]
        x, r = st["x"], st["r"]
        r_tld, rho = st["r_tld"], st["rho"]
        p = st["p"]
        p_hat = self._precond(data, p)
        v = spmv(A, p_hat)
        alpha = _safe_div(rho, blas.dot(r_tld, v))
        s = r - alpha * v
        s_hat = self._precond(data, s)
        t = spmv(A, s_hat)
        omega = _safe_div(blas.dot(t, s), blas.dot(t, t))
        x = x + alpha * p_hat + omega * s_hat
        r = s - omega * t
        rho_new = blas.dot(r_tld, r)
        beta = _safe_div(rho_new * alpha, rho * omega)
        p = r + beta * (p - omega * v)
        out = {**st, "x": x, "r": r, "p": p, "v": v, "rho": rho_new,
               "alpha": alpha, "omega": omega}
        if self.health_guards:
            out["breakdown"] = (rho_new == 0) | (omega == 0)
        return out

    def _fused_iteration(self, data, st):
        """Preconditioned twin of BiCGStab's fused iteration: both
        SpMVs act on preconditioned vectors while the dot operands
        (r_tld, s) stream through the kernels' epilogue slot."""
        A = data["A"]
        x, r = st["x"], st["r"]
        r_tld, rho = st["r_tld"], st["rho"]
        p = st["p"]
        p_hat = self._precond(data, p)
        v, rtv = spmv_ddot(A, p_hat, r_tld)
        (rtv,) = blas.psum_bundle((rtv,))
        alpha = _safe_div(rho, rtv)
        a = alpha.astype(r.dtype)
        s = r - a * v
        s_hat = self._precond(data, s)
        t, ts, tt = spmv_ddot(A, s_hat, s, self_dot=True)
        ts, tt = blas.psum_bundle((ts, tt))
        omega = _safe_div(ts, tt)
        w = omega.astype(r.dtype)
        x = x + a * p_hat + w * s_hat
        r = s - w * t
        (rho_new,) = blas.psum_bundle((_ldot(r_tld, r),))
        beta = _safe_div(rho_new * alpha, rho * omega)
        p = r + beta.astype(r.dtype) * (p - w * v)
        out = {**st, "x": x, "r": r, "p": p, "v": v, "rho": rho_new,
               "alpha": alpha, "omega": omega}
        if self.health_guards:
            out["breakdown"] = (rho_new == 0) | (omega == 0)
        return out


@registry.solvers.register("CHEBYSHEV")
class ChebyshevSolver(_KrylovBase):
    """Chebyshev iteration (cheb_solver.cu:150-216) with eigenvalue-
    estimation modes: 0/1 = power iteration on the (preconditioned)
    operator at setup (mode 0's separate lmin eigensolve collapses to the
    lmax/8 smoothing interval here — one power sweep, documented
    deviation); 2 = Gershgorin max row sum (0.9 under a preconditioner);
    3 = user cheby_max_lambda/cheby_min_lambda under a preconditioner,
    Gershgorin otherwise."""

    uses_preconditioner = True
    is_smoother = True
    # _d/_c are Python floats baked into the trace (see
    # _resetup_kept_static below) — one trace cannot serve per-system
    # spectra, so multi-matrix batching rejects this solver
    trace_bakes_values = True

    def __init__(self, cfg, scope="default", name="CHEBYSHEV"):
        super().__init__(cfg, scope, name)
        self.estimate_mode = int(cfg.get("chebyshev_lambda_estimate_mode",
                                         scope))
        self.lmax = float(cfg.get("cheby_max_lambda", scope))
        self.lmin = float(cfg.get("cheby_min_lambda", scope))

    def solver_setup(self):
        mode = self.estimate_mode
        has_precond = self.preconditioner is not None
        if mode in (0, 1):
            precond_apply = None
            if has_precond:
                pdata = self.preconditioner.solve_data()
                precond_apply = lambda v: self.preconditioner.apply(pdata, v)
            lmax = _power_lambda_max(self.A, precond_apply)
            self.lmax = float(lmax) * 1.05
            self.lmin = self.lmax / 8.0  # standard smoothing interval
        elif mode == 2:
            if has_precond:
                # reference assumption: preconditioner compresses the
                # spectrum to ~1 (cheb_solver.cu:193-196)
                self.lmax = 0.9
            else:
                self.lmax = float(_gershgorin_lambda_max(self.A))
            self.lmin = self.lmax * 0.125
        elif mode == 3:
            if has_precond:
                pass  # user-provided cheby_max_lambda / cheby_min_lambda
            else:
                self.lmax = float(_gershgorin_lambda_max(self.A))
                self.lmin = self.lmax * 0.125
        self._d = (self.lmax + self.lmin) / 2.0
        self._c = (self.lmax - self.lmin) / 2.0

    def _resetup_kept_static(self):
        # _d/_c are VALUE-derived Python floats baked into the trace as
        # constants (solve_iteration reads them directly) — a value-only
        # resetup changes them, so the cached solve must re-trace
        return False

    def computes_residual(self):
        return False

    def solve_init(self, data, b, x, r):
        dt = x.dtype
        return {"p": jnp.zeros_like(x), "rho": jnp.zeros((), dt),
                "k": jnp.zeros((), jnp.int32)}

    def solve_iteration(self, data, b, st):
        A = data["A"]
        d, c = self._d, self._c
        sigma = d / c
        x, p, rho, k = st["x"], st["p"], st["rho"], st["k"]
        r = b - spmv(A, x)
        z = self._precond(data, r)
        first = (k == 0)
        rho_new = jnp.where(first, 1.0 / sigma,
                            1.0 / (2.0 * sigma - rho))
        p = jnp.where(first, z / d,
                      rho_new * rho * p + (2.0 * rho_new / c) * z)
        x = x + p
        return {**st, "x": x, "p": p, "rho": rho_new, "k": k + 1}


def _gershgorin_lambda_max(A):
    """Max diag-scaled absolute row sum — the reference's
    compute_eigenmax_estimate (cheb_solver.cu:46-74) lambda bound."""
    absA = A.with_values(jnp.abs(A.values),
                         jnp.abs(A.diag) if A.has_external_diag else None)
    n = A.num_rows * A.block_dimx
    row_abs = spmv(absA, jnp.ones(n, dtype=A.dtype))
    d = A.diagonal()
    if d.ndim == 3:  # block diagonal -> per-unknown diagonal entries
        d = jnp.diagonal(d, axis1=1, axis2=2).reshape(-1)
    return jnp.max(row_abs / jnp.abs(d))


def _power_lambda_max(A, precond_apply=None, iters: int = 20, seed: int = 0):
    """Power-iteration estimate of lambda_max of the (preconditioned)
    operator M^{-1}A (setup-time; cheb_solver.cu eigenvalue estimation)."""
    import numpy as np
    n = A.num_rows * A.block_dimx
    v = jnp.asarray(np.random.default_rng(seed).standard_normal(n),
                    dtype=A.dtype)

    def op(v):
        w = spmv(A, v)
        return precond_apply(w) if precond_apply is not None else w

    def body(_, carry):
        v, lam = carry
        w = op(v)
        lam = blas.nrm2(w)
        return w / jnp.where(lam == 0, 1.0, lam), lam

    _, lam = jax.lax.fori_loop(0, iters, body,
                               (v / blas.nrm2(v), jnp.zeros((), v.dtype)))
    return lam
