"""Polynomial smoothers: POLYNOMIAL, KPZ_POLYNOMIAL, CHEBYSHEV_POLY.

TPU-native analogs of src/solvers/polynomial_solver.cu (351 LoC),
kpz_polynomial_solver.cu (227), chebyshev_poly.cu (371). Polynomial
smoothers are ideal TPU smoothers: no coloring, no triangular solves —
each application is `order` SpMVs plus AXPYs, which XLA fuses into a
short straight-line program.

- POLYNOMIAL: Chebyshev relaxation on the interval [rho/30, 1.1*rho]
  (the bundled-CUSP convention the reference delegates to,
  polynomial_solver.cu:146-155: ritz_spectral_radius_symmetric +
  chebyshev_polynomial_coefficients); rho estimated at setup with a
  short device Lanczos, degree = kpz_order.
- KPZ_POLYNOMIAL: the KPZ three-term recurrence exactly as in
  kpz_polynomial_solver.cu:140-193 (smax = ||A||_inf via the transpose
  row sums, smin = smax/kpz_mu, delta/beta/chi coefficients).
- CHEBYSHEV_POLY: the "magic damping" tau sequence of chebyshev_poly.cu
  (tau_i = cos^2(beta) / (cos^2(beta(2i+1)) - sin^2(beta)) / lambda,
  beta = pi/(4m+2), lambda = Gershgorin max row sum,
  chebyshev_poly.cu:65-74,188-198), applied as x += tau_i (b - A x).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import registry
from ..errors import BadParametersError
from ..ops.spmv import spmv
from .base import Solver


def chebyshev_poly_coeffs(m: int):
    """The 'magic damping' tau numerators (chebyshev_poly.cu damping
    schedule); divide by the spectral bound to get the taus. Single
    implementation shared by the single-device and sharded setups."""
    beta = np.pi / (4.0 * m + 2.0)
    return np.asarray([
        np.cos(beta) ** 2
        / (np.cos(beta * (2 * i + 1)) ** 2 - np.sin(beta) ** 2)
        for i in range(m)
    ])


def _abs_row_sums(A):
    rows, cols, vals = A.coo()
    s = jax.ops.segment_sum(jnp.abs(vals), rows, num_segments=A.num_rows,
                            indices_are_sorted=True)
    if A.has_external_diag:
        s = s + jnp.abs(A.diag)
    return s


def _lanczos_rho(A, steps: int = 8) -> float:
    """Spectral-radius estimate by a short Lanczos run
    (cusp ritz_spectral_radius_symmetric analog). Host-orchestrated at
    setup; each step is one device SpMV."""
    n = A.num_rows
    rng = np.random.default_rng(17)
    v = jnp.asarray(rng.standard_normal(n), A.dtype)
    v = v / jnp.linalg.norm(v)
    steps = min(steps, n)
    alphas, betas = [], []
    v_prev = jnp.zeros_like(v)
    beta = 0.0
    for _ in range(steps):
        w = spmv(A, v) - beta * v_prev
        alpha = float(jnp.dot(v, w))
        w = w - alpha * v
        beta = float(jnp.linalg.norm(w))
        alphas.append(alpha)
        betas.append(beta)
        if beta < 1e-12:
            break
        v_prev, v = v, w / beta
    k = len(alphas)
    T = np.diag(alphas)
    for i in range(k - 1):
        T[i, i + 1] = T[i + 1, i] = betas[i]
    return float(np.max(np.abs(np.linalg.eigvalsh(T)))) * 1.01


@registry.solvers.register("POLYNOMIAL")
class PolynomialSolver(Solver):
    """Chebyshev relaxation smoother (polynomial_solver.cu scalar path).
    One application = `kpz_order` SpMVs via the stable three-term
    Chebyshev semi-iteration on [rho/30, 1.1 rho]."""

    is_smoother = True

    def __init__(self, cfg, scope="default", name="POLYNOMIAL"):
        super().__init__(cfg, scope, name)
        order = int(cfg.get("kpz_order", scope))
        self.order = order if order > 0 else 6   # ndeg0==0 -> 6 (:114)

    def solver_setup(self):
        if self.A.is_block:
            raise BadParametersError(
                "POLYNOMIAL smoother supports scalar matrices")
        rho = _lanczos_rho(self.A)
        self.lmax = 1.1 * rho
        self.lmin = rho / 30.0

    def solve_data(self):
        d = super().solve_data()
        d["lmin"] = jnp.asarray(self.lmin, self.A.dtype)
        d["lmax"] = jnp.asarray(self.lmax, self.A.dtype)
        return d

    def computes_residual(self):
        return False

    def solve_iteration(self, data, b, st):
        A = data["A"]
        lmin, lmax = data["lmin"], data["lmax"]
        theta = 0.5 * (lmax + lmin)
        delta = 0.5 * (lmax - lmin)
        x = st["x"]
        r = b - spmv(A, x)
        # Chebyshev semi-iteration (fixed `order` steps, unrolled)
        sigma = theta / delta
        rho_c = 1.0 / sigma
        d = r / theta
        for _ in range(self.order):
            x = x + d
            r = r - spmv(A, d)
            rho_new = 1.0 / (2.0 * sigma - rho_c)
            d = rho_new * rho_c * d + 2.0 * rho_new / delta * r
            rho_c = rho_new
        out = dict(st)
        out["x"] = x
        return out


@registry.solvers.register("KPZ_POLYNOMIAL")
class KPZPolynomialSolver(Solver):
    """KPZ polynomial smoother (kpz_polynomial_solver.cu:140-193)."""

    is_smoother = True

    def __init__(self, cfg, scope="default", name="KPZ_POLYNOMIAL"):
        super().__init__(cfg, scope, name)
        self.mu = int(cfg.get("kpz_mu", scope))
        self.order = max(int(cfg.get("kpz_order", scope)), 1)

    def solver_setup(self):
        if self.A.is_block:
            raise BadParametersError(
                "KPZ_POLYNOMIAL supports scalar matrices")
        # l_inf = max column abs-sum (computed on A^T in the reference,
        # kpz_polynomial_solver.cu:89-99)
        rows, cols, vals = self.A.coo()
        colsum = jax.ops.segment_sum(jnp.abs(vals), cols,
                                     num_segments=self.A.num_cols)
        if self.A.has_external_diag:
            colsum = colsum + jnp.abs(self.A.diag)
        self.l_inf = float(jnp.max(colsum))

    def solve_data(self):
        d = super().solve_data()
        d["l_inf"] = jnp.asarray(self.l_inf, self.A.dtype)
        return d

    def computes_residual(self):
        return False

    def solve_iteration(self, data, b, st):
        A = data["A"]
        smax = data["l_inf"]
        smin = smax / self.mu
        smu0 = 1.0 / smax
        smu1 = 1.0 / smin
        skappa = jnp.sqrt(smax / smin)
        delta = (skappa - 1.0) / (skappa + 1.0)
        beta = (jnp.sqrt(smu0) + jnp.sqrt(smu1)) ** 2
        chi = 4.0 * smu0 * smu1 / beta
        x = st["x"]
        r = b - spmv(A, x)
        v0 = (smu0 + smu1) / 2.0 * r
        v = beta / 2.0 * r - smu0 * smu1 * spmv(A, r)
        for _ in range(2, self.order + 1):
            sn = r - spmv(A, v)
            sn = chi * sn + delta * delta * v - delta * delta * v0
            v0 = v
            v = v + sn
        out = dict(st)
        out["x"] = x + v
        return out


@registry.solvers.register("CHEBYSHEV_POLY")
class ChebyshevPolySolver(Solver):
    """'Magic damping' Chebyshev smoother (chebyshev_poly.cu). One
    application = `chebyshev_polynomial_order` damped Richardson steps
    x += tau_i (b - A x)."""

    is_smoother = True
    # matrix-free capable (amg/hierarchy.py `matrix_free` knob): the
    # damped-Richardson steps need only the stencil coefficients; no
    # diagonal inverse is synthesized (dinv-free schedule)
    supports_matrix_free = True
    matrix_free_dinv = None

    def __init__(self, cfg, scope="default", name="CHEBYSHEV_POLY"):
        super().__init__(cfg, scope, name)
        order = int(cfg.get("chebyshev_polynomial_order", scope))
        self.order = min(10, max(order, 1))      # clamp (:102-103)
        self.fused_smoother = bool(int(cfg.get("fused_smoother", scope)))

    def solver_setup(self):
        if self.A.is_block:
            raise BadParametersError(
                "CHEBYSHEV_POLY supports scalar matrices")
        # lambda stays ON DEVICE: a float() fetch here costs a full
        # tunnel round trip per AMG level (~170 ms each on the bench
        # rig); taus ships to the solve program as a device array
        lam = jnp.max(_abs_row_sums(self.A))   # Gershgorin bound
        self._taus = jnp.asarray(chebyshev_poly_coeffs(self.order),
                                 self.A.dtype) / lam.astype(self.A.dtype)

    def solve_data(self):
        d = super().solve_data()
        d["taus"] = self._taus
        st = getattr(self, "_mf_stencil", None)
        if st is not None:
            # matrix-free level: drop the A value slab from the
            # operator view; no fused slabs — the kernels read the
            # stencil coefficients from SMEM (ops/stencil.py)
            from ..ops.stencil import mf_slim
            d["A"] = mf_slim(d["A"])
            d["stencil"] = st
            return d
        if self.fused_smoother and self.A is not None \
                and not getattr(self.A, "is_block", True):
            from ..ops import smooth as fused
            slabs = fused.solver_fused_slabs(self, self.A)
            if slabs is not None:
                d["fused"] = slabs
        return d

    def computes_residual(self):
        return False

    def solve_iteration(self, data, b, st):
        A = data["A"]
        x = st["x"]
        for i in range(self.order):
            x = x + data["taus"][i] * (b - spmv(A, x))
        out = dict(st)
        out["x"] = x
        return out

    # -- fused smoothing (ops/smooth.py) --------------------------------
    # One smoother application is `order` damped-Richardson steps
    # x += tau_i (b - A x); `sweeps` applications are the tiled tau
    # schedule, which the fused kernels run (with the trailing cycle
    # residual) in as few HBM passes over A as the plan budget allows.
    def _fused_taus(self, data, sweeps: int, dtype):
        taus = jnp.asarray(data["taus"], dtype)
        return jnp.tile(taus, sweeps) if sweeps > 1 else taus

    def smooth(self, data, b, x, sweeps: int):
        st = data.get("stencil")
        if st is not None:
            if sweeps < 1:
                return x
            from ..ops import stencil as mf
            return mf.stencil_fused_smooth(
                st, self._fused_taus(data, sweeps, x.dtype), b, x,
                with_residual=False)
        if sweeps > 0 and self.fused_smoother:
            from ..ops import smooth as fused
            out = fused.fused_smooth(
                data, b, x, self._fused_taus(data, sweeps, x.dtype),
                with_residual=False)
            if out is not None:
                return out
        return super().smooth(data, b, x, sweeps)

    def smooth_residual(self, data, b, x, sweeps: int):
        st = data.get("stencil")
        if st is not None:
            from ..ops import stencil as mf
            taus = (self._fused_taus(data, sweeps, x.dtype)
                    if sweeps > 0 else jnp.zeros((0,), x.dtype))
            return mf.stencil_fused_smooth(st, taus, b, x,
                                           with_residual=True)
        if sweeps > 0 and self.fused_smoother:
            from ..ops import smooth as fused
            out = fused.fused_smooth(
                data, b, x, self._fused_taus(data, sweeps, x.dtype),
                with_residual=True)
            if out is not None:
                return out
        return super().smooth_residual(data, b, x, sweeps)

    # -- cycle fusion (AMGLevel.restrict_fused / prolongate_smooth) ----
    def smooth_restrict(self, data, b, x, sweeps: int, xfer):
        if sweeps < 1:
            return None
        st = data.get("stencil")
        if st is not None:
            from ..ops import stencil as mf
            return mf.stencil_smooth_restrict(
                st, self._fused_taus(data, sweeps, x.dtype), b, x,
                xfer)
        if self.fused_smoother:
            from ..ops import smooth as fused
            return fused.fused_smooth_restrict(
                data, b, x, self._fused_taus(data, sweeps, x.dtype),
                xfer)
        return None

    def smooth_corr(self, data, b, x, xc, sweeps: int, xfer,
                    want_dot: bool = False):
        if sweeps < 1:
            return None
        st = data.get("stencil")
        if st is not None:
            from ..ops import stencil as mf
            return mf.stencil_corr_smooth(
                st, self._fused_taus(data, sweeps, x.dtype), b, x, xc,
                xfer, want_dot=want_dot)
        if self.fused_smoother:
            from ..ops import smooth as fused
            return fused.fused_corr_smooth(
                data, b, x, xc, self._fused_taus(data, sweeps, x.dtype),
                xfer, want_dot=want_dot)
        return None

    def fused_tail_spec(self, data, sweeps: int, dtype):
        """Tiled tau schedule for the coarse-tail kernel (one smoother
        application = `order` damped-Richardson steps)."""
        if not self.fused_smoother or getattr(
                data["A"], "is_block", True):
            return None
        if sweeps <= 0:
            return jnp.zeros((0,), dtype), None
        return self._fused_taus(data, sweeps, dtype), None
