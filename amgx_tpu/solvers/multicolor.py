"""Multicolor smoothers: GS, DILU, ILU(k), fixed-color GS, serial GS,
CF-Jacobi.

TPU-native analogs of the reference's color-parallel smoother family
(src/solvers/multicolor_gauss_seidel_solver.cu:1,
multicolor_dilu_solver.cu:1 — its largest kernel investment and the
default smoother in shipped configs, multicolor_ilu_solver.cu:1,
fixcolor_gauss_seidel_solver.cu:1, gauss_seidel_solver.cu:1,
cf_jacobi_solver.cu:1).

Execution model redesign for XLA: the reference launches one kernel per
color over the rows of that color. Here each color step is a *masked
dense update* over the full vector driven by one SpMV — the per-color
loop is unrolled at trace time over the (static) color count, so a whole
sweep is one fused XLA program:

- colored GS sweep:  for c: x  <- where(color==c, x + w*D^-1(b-Ax), x)
  (exact Gauss-Seidel in the color ordering: the SpMV sees the already-
  updated colors);
- DILU forward:      for c asc:  delta <- where(color==c,
                        Einv*(r - A delta), delta)
  where A delta only picks up colors < c because delta is still zero
  elsewhere — the masked-SpMV trick that replaces the reference's
  row_colors[j] < current_color predicate
  (DILU_forward_1x1_kernel, multicolor_dilu_solver.cu:1766);
- DILU backward:     for c desc: Delta <- where(color==c,
                        delta - Einv*(A Delta), Delta); x += w*Delta
  (DILU_backward kernels, :1908+);
- DILU setup:        Einv_i = 1/(a_ii - sum_{color_j < color_i}
                        a_ij * Einv_j * a_ji)
  color-by-color, with the a_ji lookup done as a key search into the
  CSR pattern (DILU_setup_1x1_kernel, :650-810).

ILU(k) factors the *color-permuted* matrix with fixed-point (Chow-Patel
style) sweeps, each one pattern-restricted L@U product; because the
elimination DAG of a C-colored matrix has depth <= C, C sweeps reproduce
the exact ILU(0) factors (E. Chow, A. Patel, "Fine-grained parallel
incomplete LU factorization", SISC 2015 — public algorithm).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import registry
from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..ops.coloring import color_matrix
from ..ops.dense import abs_det, inverse, safe_inverse
from ..ops.spmv import spmv
from .base import Solver
from .relaxation import _apply_dinv, l1_strengthened_diag, safe_recip


def _match_transpose_np(num_rows, num_cols, ro, ci, vals):
    """Host twin of _match_transpose (scalar matrices): numpy int64-key
    searchsorted over host (numpy/mirror) arrays. CSR keys are already
    sorted when columns are sorted in-row (the host hierarchy build's
    invariant), so the argsort is usually skipped entirely — the device
    form's eager int64 argsort was the single hottest op of the host
    smoother setup."""
    import numpy as np
    cols = ci.astype(np.int64)
    rows = np.repeat(np.arange(num_rows, dtype=np.int64), np.diff(ro))
    keys = rows * num_cols + cols
    if np.all(keys[1:] >= keys[:-1]):
        order = None
        skeys = keys
    else:
        order = np.argsort(keys, kind="stable")
        skeys = keys[order]
    want = cols * num_cols + rows
    pos = np.clip(np.searchsorted(skeys, want), 0, max(keys.shape[0] - 1, 0))
    found = skeys[pos] == want
    src = pos if order is None else order[pos]
    return np.where(found, vals[src], 0.0)


def _match_transpose(A: CsrMatrix):
    """For every CSR entry (i,j) return the value of (j,i), or 0 when the
    pattern has no such entry (the reference's warp search over row j,
    multicolor_dilu_solver.cu:740-781)."""
    rows, cols, vals = A.coo()
    keys = rows.astype(jnp.int64) * A.num_cols + cols.astype(jnp.int64)
    order = jnp.argsort(keys)          # CSR is usually already sorted
    skeys = keys[order]
    want = cols.astype(jnp.int64) * A.num_cols + rows.astype(jnp.int64)
    pos = jnp.clip(jnp.searchsorted(skeys, want), 0, keys.shape[0] - 1)
    src = order[pos]
    found = skeys[pos] == want
    if A.is_block:
        # the (j,i) block participates as A_ji, i.e. transposed in the
        # i-row formula; keep it as stored — the caller contracts it on
        # the correct side
        return jnp.where(found[:, None, None], vals[src], 0.0)
    return jnp.where(found, vals[src], 0.0)


class _ColoredSolver(Solver):
    """Shared coloring plumbing (Solver::setup colors the matrix when
    isColoringNeeded(), include/solvers/solver.h:140)."""

    is_smoother = True

    def __init__(self, cfg, scope="default", name="?"):
        super().__init__(cfg, scope, name)
        self.relaxation_factor = float(cfg.get("relaxation_factor", scope))

    def _color(self):
        coloring = color_matrix(self.A, self.cfg, self.scope)
        self.row_colors = coloring.row_colors
        self.num_colors = int(coloring.num_colors)

    def computes_residual(self):
        return False


@registry.solvers.register("MULTICOLOR_GS")
class MulticolorGSSolver(_ColoredSolver):
    """Color-parallel Gauss-Seidel
    (multicolor_gauss_seidel_solver.cu:1). `symmetric_GS=1` appends the
    reverse color sweep."""

    def __init__(self, cfg, scope="default", name="MULTICOLOR_GS"):
        super().__init__(cfg, scope, name)
        self.symmetric = bool(int(cfg.get("symmetric_GS", scope)))

    def solver_setup(self):
        self._color()
        d = self.A.diagonal()
        self._dinv = safe_inverse(d) if self.A.is_block else safe_recip(d)

    def solve_data(self):
        d = super().solve_data()
        d["dinv"] = self._dinv
        d["colors"] = self.row_colors
        return d

    def _color_update(self, data, b, x, c):
        A = data["A"]
        r = b - spmv(A, x)
        upd = x + self.relaxation_factor * _apply_dinv(
            data["dinv"], r, A.is_block)
        mask = data["colors"] == c
        if A.is_block:
            mask = jnp.repeat(mask, A.block_dimx,
                              total_repeat_length=x.shape[0])
        return jnp.where(mask, upd, x)

    def solve_iteration(self, data, b, st):
        x = st["x"]
        nc = self.num_colors
        # rolled color loop (traced color index — see the DILU sweep)
        x = jax.lax.fori_loop(
            0, nc, lambda c, x: self._color_update(data, b, x, c), x)
        if self.symmetric:
            x = jax.lax.fori_loop(
                0, nc,
                lambda i, x: self._color_update(data, b, x, nc - 1 - i),
                x)
        out = dict(st)
        out["x"] = x
        return out


@registry.solvers.register("FIXCOLOR_GS")
class FixcolorGSSolver(MulticolorGSSolver):
    """Fixed 4-color striped GS (fixcolor_gauss_seidel_solver.cu:1):
    colors are assigned round-robin by row index instead of from the
    graph — valid for banded stencils, cheap to set up."""

    FIXED_COLORS = 4

    def _color(self):
        n = self.A.num_rows
        self.row_colors = jnp.arange(n, dtype=jnp.int32) % self.FIXED_COLORS
        self.num_colors = min(self.FIXED_COLORS, max(n, 1))


@registry.solvers.register("GS")
class GSSolver(Solver):
    """Serial natural-order Gauss-Seidel (gauss_seidel_solver.cu:1).
    Exact sequential sweep as a lax.fori_loop over rows with padded-ELL
    row gathers — inherently O(n) sequential steps; the reference's GS is
    serial too. Use MULTICOLOR_GS for large problems."""

    is_smoother = True

    def __init__(self, cfg, scope="default", name="GS"):
        super().__init__(cfg, scope, name)
        self.relaxation_factor = float(cfg.get("relaxation_factor", scope))
        if bool(int(cfg.get("GS_L1_variant", scope))):
            self._l1 = True
        else:
            self._l1 = False

    def solver_setup(self):
        if self.A.is_block:
            raise BadParametersError("GS: scalar matrices only")
        from ..ops.spgemm import _fold_diag
        A = _fold_diag(self.A)          # row_dot must include a_ii * x_i
        if A.ell_cols is None:
            A = CsrMatrix(
                row_offsets=A.row_offsets, col_indices=A.col_indices,
                values=A.values, num_rows=A.num_rows,
                num_cols=A.num_cols).init(ell="always")
        self._ell_cols, self._ell_vals = A.ell_cols, A.ell_vals
        d = l1_strengthened_diag(self.A) if self._l1 else self.A.diagonal()
        self._diag = d
        self._dinv = safe_recip(d)

    def solve_data(self):
        d = super().solve_data()
        d.update(ell_cols=self._ell_cols, ell_vals=self._ell_vals,
                 gs_diag=self._diag, dinv=self._dinv)
        return d

    def computes_residual(self):
        return False

    def solve_iteration(self, data, b, st):
        cols, vals = data["ell_cols"], data["ell_vals"]
        diag, dinv = data["gs_diag"], data["dinv"]
        w = self.relaxation_factor

        def row_update(i, x):
            row_dot = jnp.dot(vals[i], x[cols[i]])
            # row_dot includes a_ii * x_i; remove it for the GS update
            xi_new = dinv[i] * (b[i] - row_dot + diag[i] * x[i])
            return x.at[i].set((1 - w) * x[i] + w * xi_new)

        x = jax.lax.fori_loop(0, self.A.num_rows, row_update, st["x"])
        out = dict(st)
        out["x"] = x
        return out


@registry.solvers.register("MULTICOLOR_DILU")
class MulticolorDILUSolver(_ColoredSolver):
    """Diagonal-ILU smoother (multicolor_dilu_solver.cu:1 — 4259 LoC in
    the reference, its single largest kernel file). M = (E+L)E^{-1}(E+U)
    where L/U split A by color order and E is chosen so diag(M)=diag(A):

        E_i = A_ii - sum_{color_j < color_i} A_ij E_j^{-1} A_ji.
    """

    def solver_setup(self):
        from ..matrix import host_arrays
        self._color()
        A = self.A
        ha = None if A.is_block else host_arrays(
            A.row_offsets, A.col_indices, A.values)
        if ha is not None and A.has_external_diag \
                and host_arrays(A.diag) is None:
            ha = None             # device-only external diagonal
        if ha is not None:
            # host fast path (host-resident OR mirror-backed device
            # matrices): the whole color recurrence in synchronous
            # numpy — the eager per-color dispatches and the int64-key
            # argsort dominated the smoother setup otherwise (minutes
            # at 96^3 on a tunneled accelerator)
            import numpy as onp
            ro, cols, vals = ha
            n = A.num_rows
            at_vals = _match_transpose_np(n, A.num_cols, ro, cols, vals)
            if A.has_external_diag:
                d = host_arrays(A.diag)[0]
            else:
                hdi = host_arrays(A.diag_idx) if A.diag_idx is not None \
                    else None
                if hdi is not None:
                    # init already stored the first-occurrence in-row
                    # diagonal index (padded-duplicate CSR convention)
                    di = hdi[0]
                    d = onp.where(di >= 0,
                                  vals[onp.maximum(di, 0)], 0.0)
                else:
                    # fallback: scan (uninitialized host matrices)
                    rows64 = onp.repeat(onp.arange(n, dtype=onp.int64),
                                        onp.diff(ro))
                    cand = onp.where(cols == rows64,
                                     onp.arange(cols.shape[0]),
                                     cols.shape[0])
                    from ..matrix import _np_row_reduce
                    dmin = _np_row_reduce(onp.minimum, cand, ro, n,
                                          cols.shape[0])
                    d = onp.where(
                        dmin < cols.shape[0],
                        vals[onp.minimum(dmin, cols.shape[0] - 1)], 0.0)
            colors = onp.asarray(self.row_colors)
            Einv = onp.zeros(n, vals.dtype)
            from ..matrix import _np_row_reduce
            prod = vals * at_vals
            for c in range(self.num_colors):
                e = _np_row_reduce(onp.add, prod * Einv[cols], ro, n, 0.0)
                blk = d - e
                new = onp.divide(1.0, blk,
                                 out=onp.zeros_like(blk),
                                 where=blk != 0)
                Einv = onp.where(colors == c, new, Einv)
            self._Einv = Einv
            return
        rows, cols, vals = A.coo()
        at_vals = _match_transpose(A)
        d = A.diagonal()
        colors = self.row_colors
        n = A.num_rows
        if A.is_block:
            bx = A.block_dimx
            Einv = jnp.zeros((n, bx, bx), A.dtype)
            eye = jnp.eye(bx, dtype=A.dtype)
            for c in range(self.num_colors):
                # contributions A_ij Einv_j A_ji; Einv_j is zero for
                # colors >= c (incl. the diagonal j==i), so the masked
                # predicate of the reference kernel falls out for free
                contrib = jnp.einsum("nab,nbc,ncd->nad",
                                     vals, Einv[cols], at_vals)
                e = jax.ops.segment_sum(contrib, rows, num_segments=n,
                                        indices_are_sorted=True)
                blk = d - e
                # singular guard: fall back to identity like the scalar 1/0
                det_ok = abs_det(blk) > 0
                blk = jnp.where(det_ok[:, None, None], blk, eye[None])
                Einv = jnp.where((colors == c)[:, None, None],
                                 inverse(blk), Einv)
        else:
            Einv = jnp.zeros((n,), A.dtype)
            for c in range(self.num_colors):
                contrib = vals * Einv[cols] * at_vals
                e = jax.ops.segment_sum(contrib, rows, num_segments=n,
                                        indices_are_sorted=True)
                Einv = jnp.where(colors == c, safe_recip(d - e), Einv)
        self._Einv = Einv

    def solve_data(self):
        d = super().solve_data()
        d["Einv"] = self._Einv
        d["colors"] = self.row_colors
        return d

    def _mask(self, data, c, like):
        m = data["colors"] == c
        if self.A.is_block:
            m = jnp.repeat(m, self.A.block_dimx,
                           total_repeat_length=like.shape[0])
        return m

    def solve_iteration(self, data, b, st):
        A, Einv = data["A"], data["Einv"]
        x = st["x"]
        r = b - spmv(A, x)
        nc = self.num_colors
        # color sweeps as lax.fori_loop (the mask compares against the
        # TRACED color index): a Python unroll put 2*colors SpMVs per
        # level into one XLA program, which at 128^3-classical scale
        # (8 levels x ~8 colors) faulted the TPU at compile/run time

        def fwd(c, delta):
            # forward: (E+L) delta = r, colors ascending (only colors
            # < c are nonzero in delta)
            upd = _apply_dinv(Einv, r - spmv(A, delta), A.is_block)
            return jnp.where(self._mask(data, c, x), upd, delta)

        delta = jax.lax.fori_loop(0, nc, fwd, jnp.zeros_like(x))

        def bwd(i, Delta):
            # backward: (E+U) Delta = E delta, colors descending (only
            # colors > c are nonzero in Delta)
            c = nc - 1 - i
            upd = delta - _apply_dinv(Einv, spmv(A, Delta), A.is_block)
            return jnp.where(self._mask(data, c, x), upd, Delta)

        Delta = jax.lax.fori_loop(0, nc, bwd, jnp.zeros_like(x))
        out = dict(st)
        out["x"] = x + self.relaxation_factor * Delta
        return out


def _permute_csr(A: CsrMatrix, perm, iperm) -> CsrMatrix:
    """P A P^T: row/col relabeling by new = iperm[old] (the reference's
    reorderColumnsByColor + row sort, src/matrix.cu)."""
    rows, cols, vals = A.coo()
    return CsrMatrix.from_coo(iperm[rows], iperm[cols], vals,
                              A.num_rows, A.num_cols)


@registry.solvers.register("MULTICOLOR_ILU")
class MulticolorILUSolver(_ColoredSolver):
    """ILU(k) smoother on the color-permuted matrix
    (multicolor_ilu_solver.cu:1). Factors via fixed-point sweeps, each a
    pattern-restricted Lstrict@U product; C sweeps are exact for a
    C-colored matrix (elimination depth <= C). Triangular solves run
    color-by-color with the same masked-SpMV scheme as DILU.

    ilu_sparsity_level=k extends the pattern by k rounds of level-fill;
    fill edges must stay properly colored, so k>0 requires a distance-2
    coloring (coloring_level=2) — validated at setup."""

    def __init__(self, cfg, scope="default", name="MULTICOLOR_ILU"):
        super().__init__(cfg, scope, name)
        self.sparsity_level = int(cfg.get("ilu_sparsity_level", scope))

    def solver_setup(self):
        if self.A.is_block:
            raise BadParametersError(
                "MULTICOLOR_ILU: scalar matrices only in this build; use "
                "MULTICOLOR_DILU for block matrices")
        self._color()
        from ..ops.spgemm import _fold_diag
        A, n = _fold_diag(self.A), self.A.num_rows
        colors = self.row_colors
        # color-sort permutation: position p holds original row perm[p]
        perm = jnp.argsort(colors, stable=True)
        iperm = jnp.zeros_like(perm).at[perm].set(
            jnp.arange(n, dtype=perm.dtype))
        Ap = _permute_csr(A, perm, iperm)
        colors_p = colors[perm]
        if self.sparsity_level > 0:
            Ap = self._extend_pattern(Ap)
        Ap = Ap.init(ell="never")
        rows, cols, vals = Ap.coo()
        # validate: factor pattern must have no same-color off-diagonals
        same = (rows != cols) & (colors_p[rows] == colors_p[cols])
        if bool(jnp.any(same)):
            raise BadParametersError(
                "MULTICOLOR_ILU: fill pattern joins same-colored rows; "
                "use coloring_level=2 (distance-2 coloring) with "
                f"ilu_sparsity_level={self.sparsity_level}")
        lower = rows > cols
        upper = ~lower
        keys = rows.astype(jnp.int64) * n + cols.astype(jnp.int64)
        # initial guess: l = a_ij/a_jj, u = a_ij (standard CP init)
        diag_full = Ap.diagonal()
        l = jnp.where(lower, vals * safe_recip(diag_full)[cols], 0.0)
        u = jnp.where(upper, vals, 0.0)
        sweeps = min(self.num_colors, 24) + 1
        from ..ops.spgemm import csr_multiply
        for _ in range(sweeps):
            Lm = CsrMatrix.from_coo(rows[lower], cols[lower], l[lower],
                                    n, n)
            Um = CsrMatrix.from_coo(rows[upper], cols[upper], u[upper],
                                    n, n)
            Pm = csr_multiply(Lm, Um)
            pr, pc, pv = Pm.coo()
            pkeys = pr.astype(jnp.int64) * n + pc.astype(jnp.int64)
            pos = jnp.clip(jnp.searchsorted(pkeys, keys), 0,
                           max(int(pkeys.shape[0]) - 1, 0))
            if pkeys.shape[0] == 0:
                prod = jnp.zeros_like(vals)
            else:
                prod = jnp.where(pkeys[pos] == keys, pv[pos], 0.0)
            u_diag = jnp.where(Ap.diag_idx < 0, 0.0,
                               u[jnp.maximum(Ap.diag_idx, 0)])
            # (Lstrict@U)_ij includes the k=j term l_ij*u_jj for i>j
            u_jj = u_diag[cols]
            l_new = safe_recip(u_jj) * (vals - (prod - l * u_jj))
            u_new = vals - prod
            l = jnp.where(lower, l_new, 0.0)
            u = jnp.where(upper, u_new, 0.0)
        # store the factors in the ORIGINAL row ordering: a proper
        # coloring has no same-color off-diagonals (validated above), so
        # the color-masked sweeps are ordering-independent — and
        # original-order factors are row-partitionable, which makes this
        # smoother distribution-aware (no global permutation at solve
        # time)
        ro, co = perm[rows[lower]], perm[cols[lower]]
        self._Lp = CsrMatrix.from_coo(ro, co, l[lower], n,
                                      n).init(ell="never")
        ro, co = perm[rows[upper]], perm[cols[upper]]
        self._Up = CsrMatrix.from_coo(ro, co, u[upper], n,
                                      n).init(ell="never")
        u_diag_p = jnp.where(Ap.diag_idx < 0, 0.0,
                             u[jnp.maximum(Ap.diag_idx, 0)])
        self._u_diag = jnp.zeros_like(u_diag_p).at[perm].set(u_diag_p)

    def _extend_pattern(self, Ap: CsrMatrix) -> CsrMatrix:
        """Level-fill pattern extension: union A with the pattern of
        Lpat@Upat, `sparsity_level` times (zero values on fill)."""
        from ..ops.spgemm import csr_add, csr_multiply
        n = Ap.num_rows
        for _ in range(self.sparsity_level):
            rows, cols, vals = Ap.coo()
            lo, up = rows > cols, rows < cols
            Lpat = CsrMatrix.from_coo(rows[lo], cols[lo],
                                      jnp.ones(int(lo.sum())), n, n)
            Upat = CsrMatrix.from_coo(rows[up], cols[up],
                                      jnp.ones(int(up.sum())), n, n)
            F = csr_multiply(Lpat, Upat)
            fr, fc, _ = F.coo()
            fill = CsrMatrix.from_coo(fr, fc, jnp.zeros(fr.shape[0]), n, n)
            Ap = csr_add(Ap, fill)
        return Ap

    def solve_data(self):
        d = super().solve_data()
        d.update(ilu_L=self._Lp, ilu_U=self._Up, u_diag=self._u_diag,
                 colors=self.row_colors)
        return d

    def solve_iteration(self, data, b, st):
        A = data["A"]
        Lp, Up = data["ilu_L"], data["ilu_U"]
        u_dinv = safe_recip(data["u_diag"])
        colors = data["colors"]
        x = st["x"]
        r = b - spmv(A, x)
        nc = self.num_colors
        # rolled color sweeps (traced color index — see the DILU sweep:
        # a Python unroll emits 2*colors SpMVs per level into one XLA
        # program, which faulted the TPU at 128^3-classical scale)
        # L y = r (unit diag), colors ascending (original ordering:
        # L only connects strictly lower colors)
        y = jax.lax.fori_loop(
            0, nc,
            lambda c, y: jnp.where(colors == c, r - spmv(Lp, y), y),
            jnp.zeros_like(r))
        # U z = y, colors descending (diagonal term zero pre-assignment)
        z = jax.lax.fori_loop(
            0, nc,
            lambda i, z: jnp.where(colors == nc - 1 - i,
                                   u_dinv * (y - spmv(Up, z)), z),
            jnp.zeros_like(r))
        out = dict(st)
        out["x"] = x + self.relaxation_factor * z
        return out


@registry.solvers.register("CF_JACOBI")
class CFJacobiSolver(Solver):
    """CF-ordered Jacobi for classical AMG (cf_jacobi_solver.cu:1): one
    sweep updates F-points then C-points (or the reverse), using the CF
    map produced by the level's selector. `cf_smoothing_mode` picks the
    order (0: C-then-F presmooth / F-then-C postsmooth flavor; here the
    mode picks the fixed order, 0=CF 1=FC, matching the implemented
    reference modes src/core.cu:416)."""

    is_smoother = True
    needs_cf_map = True

    def __init__(self, cfg, scope="default", name="CF_JACOBI"):
        super().__init__(cfg, scope, name)
        self.relaxation_factor = float(cfg.get("relaxation_factor", scope))
        self.mode = int(cfg.get("cf_smoothing_mode", scope))
        self.cf_map = None

    def set_cf_map(self, cf_map):
        self.cf_map = jnp.asarray(cf_map)

    def solver_setup(self):
        if self.A.is_block:
            raise BadParametersError("CF_JACOBI: scalar matrices only")
        if self.cf_map is None:
            raise BadParametersError(
                "CF_JACOBI needs the CF map of a classical AMG level "
                "(use it as a smoother under algorithm=CLASSICAL)")
        self._dinv = safe_recip(self.A.diagonal())

    def solve_data(self):
        d = super().solve_data()
        d["dinv"] = self._dinv
        d["is_coarse"] = self.cf_map == 1
        return d

    def computes_residual(self):
        return False

    def solve_iteration(self, data, b, st):
        A, dinv = data["A"], data["dinv"]
        coarse = data["is_coarse"]
        w = self.relaxation_factor
        x = st["x"]
        phases = (coarse, ~coarse) if self.mode == 0 else (~coarse, coarse)
        for mask in phases:
            r = b - spmv(A, x)
            x = jnp.where(mask, x + w * dinv * r, x)
        out = dict(st)
        out["x"] = x
        return out
