"""Pointwise/block relaxation solvers (usable standalone, as
preconditioners, or as AMG smoothers).

Analogs of src/solvers/block_jacobi_solver.cu (1445 LoC),
jacobi_l1_solver.cu, dummy_solver.cu. On TPU a Jacobi sweep is one fused
SpMV + elementwise update; block diagonals are inverted batched at setup
(XLA maps the (n, b, b) inversion onto the MXU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

import numpy as np

from .. import registry
from ..ops import smooth as fused
from ..ops.dense import safe_inverse
from ..ops.spmv import spmv
from .base import Solver


class _FusedJacobiMixin:
    """Fused smooth/smooth_residual for scalar damped-Jacobi solvers
    (x' = x + omega * dinv . (b - A x)): all sweeps and the trailing
    cycle residual run through the single-pass kernels of ops/smooth.py
    when the level layout supports them. `fused_smoother=0` (or any
    unsupported layout/backend) falls back to the base implementations
    unchanged — bit-for-bit the pre-fusion computation.

    Matrix-free levels: when the hierarchy's constant-coefficient
    detector installed a StencilOperator on this smoother
    (`_mf_stencil`, amg/hierarchy.py `matrix_free` knob), solve_data
    carries the stencil INSTEAD of the dinv vector and fused slabs —
    the A value slab and the dinv stream vanish from the level's HBM
    footprint — and every smooth entry routes through the coefficient
    forms in ops/stencil.py (which synthesize dinv in-register from
    the diagonal coefficient)."""

    # consulted by AMG._maybe_install_stencil: this smoother family's
    # sweeps are expressible from stencil coefficients alone, with the
    # diagonal inverse synthesized per `matrix_free_dinv`
    supports_matrix_free = True
    matrix_free_dinv = "jacobi"

    def _fused_eligible(self, data):
        A = data["A"]
        return (self.fused_smoother and not getattr(A, "is_block", True)
                and "dinv" in data)

    def _fused_taus(self, sweeps: int, dtype):
        return jnp.asarray(
            np.full(max(sweeps, 0), self.relaxation_factor), dtype)

    def solve_data(self):
        d = super().solve_data()
        st = getattr(self, "_mf_stencil", None)
        if st is not None:
            # matrix-free level: the stencil payload replaces BOTH the
            # dinv vector and the fused value slabs; the operator view
            # drops its value slab entirely (O(levels) memory)
            from ..ops.stencil import mf_slim
            d["A"] = mf_slim(d["A"])
            d["stencil"] = st
            return d
        d["dinv"] = self._dinv
        if self.fused_smoother and self.A is not None \
                and not getattr(self.A, "is_block", True):
            slabs = fused.solver_fused_slabs(self, self.A,
                                             dinv=self._dinv)
            if slabs is not None:
                d["fused"] = slabs
        return d

    def smooth(self, data, b, x, sweeps: int):
        st = data.get("stencil")
        if st is not None:
            if sweeps < 1:
                return x
            from ..ops import stencil as mf
            return mf.stencil_fused_smooth(
                st, self._fused_taus(sweeps, x.dtype), b, x,
                with_residual=False)
        if sweeps > 0 and self._fused_eligible(data):
            out = fused.fused_smooth(
                data, b, x, self._fused_taus(sweeps, x.dtype),
                dinv=data["dinv"], with_residual=False)
            if out is not None:
                return out
        return super().smooth(data, b, x, sweeps)

    def smooth_residual(self, data, b, x, sweeps: int):
        st = data.get("stencil")
        if st is not None:
            from ..ops import stencil as mf
            return mf.stencil_fused_smooth(
                st, self._fused_taus(max(sweeps, 0), x.dtype), b, x,
                with_residual=True)
        if sweeps > 0 and self._fused_eligible(data):
            out = fused.fused_smooth(
                data, b, x, self._fused_taus(sweeps, x.dtype),
                dinv=data["dinv"], with_residual=True)
            if out is not None:
                return out
        return super().smooth_residual(data, b, x, sweeps)

    # -- cycle fusion (AMGLevel.restrict_fused / prolongate_smooth) ----
    def smooth_restrict(self, data, b, x, sweeps: int, xfer):
        """(x', bc) with the restriction riding the presmoother
        kernel's epilogue, or None (caller composes unfused)."""
        if sweeps < 1:
            return None
        st = data.get("stencil")
        if st is not None:
            from ..ops import stencil as mf
            return mf.stencil_smooth_restrict(
                st, self._fused_taus(sweeps, x.dtype), b, x, xfer)
        if self._fused_eligible(data):
            return fused.fused_smooth_restrict(
                data, b, x, self._fused_taus(sweeps, x.dtype), xfer,
                dinv=data["dinv"])
        return None

    def smooth_corr(self, data, b, x, xc, sweeps: int, xfer,
                    want_dot: bool = False):
        """smooth(b, x + P xc) with the correction folded into the
        first kernel application, or None. want_dot additionally
        requests the x'.b dot epilogue → (x', dot|None)."""
        if sweeps < 1:
            return None
        st = data.get("stencil")
        if st is not None:
            from ..ops import stencil as mf
            return mf.stencil_corr_smooth(
                st, self._fused_taus(sweeps, x.dtype), b, x, xc, xfer,
                want_dot=want_dot)
        if self._fused_eligible(data):
            return fused.fused_corr_smooth(
                data, b, x, xc, self._fused_taus(sweeps, x.dtype),
                xfer, dinv=data["dinv"], want_dot=want_dot)
        return None

    def fused_tail_spec(self, data, sweeps: int, dtype):
        """(taus, dinv) schedule for the VMEM-resident coarse-tail
        kernel, or None when this smoother cannot ride it. Matrix-free
        levels return dinv=None — the tail kernel synthesizes the
        diagonal inverse from the level's stencil coefficients."""
        if not self.fused_smoother or getattr(
                data["A"], "is_block", True):
            return None
        if "stencil" in data:
            return self._fused_taus(max(sweeps, 0), dtype), None
        if "dinv" not in data:
            return None
        return self._fused_taus(max(sweeps, 0), dtype), data["dinv"]


def safe_recip(d):
    """Elementwise 1/d with 0 -> 0 (zero-in-diagonal robustness).
    Numpy in, numpy out: the host-setup path keeps smoother payloads
    numpy-backed so the hierarchy ship stays one packed transfer."""
    import numpy as np
    xp = np if isinstance(d, np.ndarray) else jnp
    safe = xp.where(d == 0, 1.0, d)
    return xp.where(d == 0, 0.0, 1.0 / safe)


def _invert_diag(A):
    """D^{-1}: scalar reciprocal or batched block inverse."""
    d = A.diagonal()
    if A.is_block:
        return safe_inverse(d)
    return safe_recip(d)


def _apply_dinv(dinv, v, block: bool):
    if block:
        vb = v.reshape(dinv.shape[0], -1)
        return jnp.einsum("nxy,ny->nx", dinv, vb).reshape(-1)
    return dinv * v


def l1_strengthened_diag(A):
    """Scalar diagonal strengthened by the off-diagonal row L1 norm in
    the diagonal's sign (jacobi_l1_solver.cu); zero diagonals stay zero
    (sign 0) so safe_recip keeps them inert."""
    from ..matrix import host_resident
    if not A.is_block and host_resident(A.row_offsets, A.col_indices,
                                        A.values, A.diag):
        import numpy as np
        n = A.num_rows
        ro = np.asarray(A.row_offsets)
        cols = np.asarray(A.col_indices)
        vals = np.asarray(A.values)
        if not A.has_external_diag and vals.dtype.kind == "f":
            # one native C++ sweep (per-level smoother-setup hot path)
            from .. import native
            out = native.l1_diag_native(n, ro, cols, vals)
            if out is not None:
                return out.astype(vals.dtype, copy=False)
        rows = np.repeat(np.arange(n, dtype=np.int32), np.diff(ro))
        l1 = np.bincount(rows, weights=np.where(rows != cols,
                                                np.abs(vals), 0.0),
                         minlength=n).astype(vals.dtype)
        d = np.asarray(A.diagonal())
        # numpy out (both branches): the host-setup ship casts numpy
        # leaves host-side before the wire
        return d + np.sign(d) * l1
    rows, cols, vals = A.coo()
    offdiag = jnp.where(rows != cols, jnp.abs(vals), 0.0)
    l1 = jax.ops.segment_sum(offdiag, rows, num_segments=A.num_rows,
                             indices_are_sorted=True)
    d = A.diagonal()
    return d + jnp.sign(d) * l1


@registry.solvers.register("BLOCK_JACOBI")
@registry.solvers.register("JACOBI")
class BlockJacobiSolver(_FusedJacobiMixin, Solver):
    """Damped (block-)Jacobi: x += omega * D^{-1} (b - A x)."""

    is_smoother = True

    def __init__(self, cfg, scope="default", name="BLOCK_JACOBI"):
        super().__init__(cfg, scope, name)
        self.relaxation_factor = float(cfg.get("relaxation_factor", scope))
        self.fused_smoother = bool(int(cfg.get("fused_smoother", scope)))

    def solver_setup(self):
        self._dinv = _invert_diag(self.A)

    def computes_residual(self):
        return False

    def solve_iteration(self, data, b, st):
        A = data["A"]
        r = b - spmv(A, st["x"])
        x = st["x"] + self.relaxation_factor * _apply_dinv(
            data["dinv"], r, A.is_block)
        out = dict(st)
        out["x"] = x
        return out


@registry.solvers.register("JACOBI_L1")
class JacobiL1Solver(_FusedJacobiMixin, Solver):
    """L1-Jacobi: the diagonal is strengthened by the off-diagonal row L1
    norm, making the sweep unconditionally convergent for SPD matrices
    (jacobi_l1_solver.cu analog)."""

    is_smoother = True
    matrix_free_dinv = "l1"

    def __init__(self, cfg, scope="default", name="JACOBI_L1"):
        super().__init__(cfg, scope, name)
        self.relaxation_factor = float(cfg.get("relaxation_factor", scope))
        self.fused_smoother = bool(int(cfg.get("fused_smoother", scope)))

    def solver_setup(self):
        A = self.A
        rows, cols, vals = A.coo()
        if A.is_block:
            # block L1: add the off-diagonal blocks' row-wise L1 norms to
            # the diagonal of each diagonal block
            offdiag = jnp.where((rows != cols)[:, None, None],
                                jnp.abs(vals), 0.0)
            l1 = jax.ops.segment_sum(offdiag.sum(axis=-1), rows,
                                     num_segments=A.num_rows,
                                     indices_are_sorted=True)
            d = A.diagonal() + jnp.eye(A.block_dimx)[None] * l1[:, :, None]
            self._dinv = safe_inverse(d)
        else:
            self._dinv = safe_recip(l1_strengthened_diag(A))

    def computes_residual(self):
        return False

    def solve_iteration(self, data, b, st):
        A = data["A"]
        r = b - spmv(A, st["x"])
        x = st["x"] + self.relaxation_factor * _apply_dinv(
            data["dinv"], r, A.is_block)
        out = dict(st)
        out["x"] = x
        return out


@registry.solvers.register("NOSOLVER")
@registry.solvers.register("DUMMY")
class NoSolver(Solver):
    """Identity 'solver' (dummy_solver.cu analog): x = b. As a
    preconditioner this is M = I."""

    is_smoother = True

    def computes_residual(self):
        return False

    def solve_iteration(self, data, b, st):
        out = dict(st)
        out["x"] = b
        return out

    def apply(self, data, rhs):
        return rhs

    def smooth(self, data, b, x, sweeps):
        return x
