"""Solver base: the composable solver tree.

TPU-native analog of Solver<TConfig> + SolverFactory
(include/solvers/solver.h:22,271; src/solvers/solver.cu). The reference
architecture is kept — any solver can own a preconditioner child solver,
configured per scope, built by a string-keyed factory — but the execution
model is redesigned for XLA:

- `setup(A)` runs once per matrix structure (host-orchestrated, device
  math) and produces a *solve-data pytree*;
- `solve()` compiles ONE XLA program: a `lax.while_loop` whose body is
  the solver's `solve_iteration`, with convergence/divergence checks as
  traced predicates — no host round-trips inside the iteration loop;
- a preconditioner application is a pure function (fixed sweep count via
  `lax.fori_loop`), so nesting solvers composes into a single fused
  program instead of the reference's nested kernel launches.

State is a plain dict pytree; the base manages the keys `x`, `r`,
`iters`, `done`, `converged`, `res_norm`, `norm0`, `res_hist`.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import registry
from ..config import Config
from ..errors import BadParametersError
from ..matrix import CsrMatrix
from ..ops import blas
from ..ops.spmv import residual as _residual
from ..output import amgx_printf
from ..resilience import faultinject as _fi
from ..resilience.status import RUNNING as _ST_RUNNING
from ..resilience.status import SolveStatus, status_string

# ---------------------------------------------------------------------------
# convergence criteria (src/convergence/, registry src/core.cu:680-685)
# ---------------------------------------------------------------------------


class Convergence:
    """Predicate deciding convergence from (res_norm, norm0)."""

    def __init__(self, cfg: Config, scope: str):
        self.tolerance = float(cfg.get("tolerance", scope))
        self.alt_rel_tolerance = float(cfg.get("alt_rel_tolerance", scope))

    def check(self, res_norm, norm0):
        raise NotImplementedError


@registry.convergence.register("ABSOLUTE")
class AbsoluteConvergence(Convergence):
    def check(self, res_norm, norm0):
        return jnp.all(res_norm <= self.tolerance)


@registry.convergence.register("RELATIVE_INI")
@registry.convergence.register("RELATIVE_INI_CORE")
class RelativeIniConvergence(Convergence):
    def check(self, res_norm, norm0):
        return jnp.all(res_norm <= self.tolerance * norm0)


@registry.convergence.register("RELATIVE_MAX")
@registry.convergence.register("RELATIVE_MAX_CORE")
class RelativeMaxConvergence(Convergence):
    """Relative to the max initial-residual component (block norms)."""

    def check(self, res_norm, norm0):
        return jnp.all(res_norm <= self.tolerance * jnp.max(norm0))


@registry.convergence.register("COMBINED_REL_INI_ABS")
class CombinedRelIniAbsConvergence(Convergence):
    def check(self, res_norm, norm0):
        return jnp.all((res_norm <= self.tolerance)
                       | (res_norm <= self.alt_rel_tolerance * norm0))


# ---------------------------------------------------------------------------
# solve result
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SolveResult:
    x: jax.Array
    iterations: int
    converged: bool
    res_norm: float | np.ndarray
    norm0: float | np.ndarray
    res_history: Optional[np.ndarray] = None
    setup_time: float = 0.0
    solve_time: float = 0.0
    # structured status (resilience/status.py SolveStatus; mirrors
    # AMGX_SOLVE_*): the in-trace health guards classify NaN storms,
    # Krylov breakdowns, stalls and divergence instead of collapsing
    # every failure into one bool
    status_code: int = int(SolveStatus.MAX_ITERS)
    # structured telemetry (telemetry/report.py SolveReport): attached
    # by the solve paths when the `telemetry` knob is on; built
    # host-side from the stats already transferred, zero added syncs
    report: Optional[Any] = None
    # solver-specific scalar stats packed onto the stats vector
    # (Solver._extra_stats_spec; e.g. REFINEMENT's accumulated inner
    # iteration count under an active solve_precision policy). None
    # when the solver declared none — the packed layout is unchanged
    extra_stats: Optional[Dict[str, float]] = None

    def __post_init__(self):
        if self.converged:
            self.status_code = int(SolveStatus.CONVERGED)

    @property
    def status(self) -> str:
        return status_string(self.status_code)


# ---------------------------------------------------------------------------
# solver base
# ---------------------------------------------------------------------------


class Solver:
    """Base solver. Subclasses implement `solver_setup`, `solve_init`,
    `solve_iteration`, and may override `apply` (preconditioner action).

    Reference skeleton: include/solvers/solver.h:126-156.
    """

    # does this solver read the "preconditioner" parameter?
    uses_preconditioner = False
    # smoothers can be used by AMG levels; they expose smooth()
    is_smoother = False
    # True when solve_iteration bakes VALUE-derived Python scalars into
    # the trace as constants (CHEBYSHEV's _d/_c): such a solver cannot
    # serve per-system coefficients from ONE trace, so the batched
    # multi-matrix path (batch/core.py) refuses it up front
    trace_bakes_values = False
    # True when solve-phase code only SpMVs against data["A"], so a
    # layout-only slim view may replace it (KACZMARZ reads COO structure
    # per sweep and opts out)
    slim_A_ok = True
    # solve_data key under which this solver stores its preconditioner's
    # subtree (REFINEMENT overrides: it names the child "inner") — the
    # diagnostics probe walks it to reach the AMG hierarchy's data at
    # any nesting depth
    _child_data_key = "precond"

    def __init__(self, cfg: Config, scope: str = "default",
                 name: str = "?"):
        self.cfg = cfg
        self.scope = scope
        self.name = name
        self.A: Optional[CsrMatrix] = None
        self.max_iters = int(cfg.get("max_iters", scope))
        self.monitor_residual = bool(cfg.get("monitor_residual", scope))
        self.norm_type = str(cfg.get("norm", scope))
        self.use_scalar_norm = bool(cfg.get("use_scalar_norm", scope))
        self.store_res_history = bool(cfg.get("store_res_history", scope))
        self.print_solve_stats = bool(cfg.get("print_solve_stats", scope))
        self.obtain_timings = bool(cfg.get("obtain_timings", scope))
        self.rel_div_tolerance = float(cfg.get("rel_div_tolerance", scope))
        # resilience guards (resilience/): classification rides the
        # residual already computed by the monitor — zero extra syncs
        self.health_guards = bool(int(cfg.get("health_guards", scope)))
        self.stall_window = int(cfg.get("stall_detection_window", scope))
        self.stall_tolerance = float(cfg.get("stall_tolerance", scope))
        # telemetry (telemetry/): report construction + watermark
        # sampling are gated per solver. telemetry_sync is a PROCESS
        # mode (span fencing is global by nature), latched — both ways
        # — by the root-construction entry points (create_solver /
        # DistributedSolver), not here: a tree's child nodes reading
        # the default would otherwise flap the flag per node
        self.telemetry = bool(int(cfg.get("telemetry", scope)))
        self.scaling = str(cfg.get("scaling", scope)).upper()
        self.scaler = None
        # Only the tree ROOT applies equation scaling: children receive
        # the already-scaled matrix, and apply()/smooth() exchange
        # vectors in the parent's (scaled) coordinates. Creation sites of
        # child solvers clear this flag. (The reference routes nested
        # solves through Solver::solve which re-scales per level —
        # consistent but redundant; here the scaled system is built once.)
        self._owns_scaling = True
        # shared precision policy (precision.py): resolves
        # solve_precision/amg_precision/tpu_dtype and rejects
        # contradictory combinations at construction time. Unset is
        # bitwise-off — nothing below reads it unless .active
        from ..precision import resolve_precision
        self._precision_policy = resolve_precision(cfg, scope)
        conv_name = str(cfg.get("convergence", scope))
        self.convergence: Convergence = registry.convergence.create(
            conv_name, cfg, scope)
        self.preconditioner: Optional[Solver] = None
        if self.uses_preconditioner:
            pname, pscope = cfg.get_solver("preconditioner", scope)
            if pname.upper() != "NOSOLVER":
                self.preconditioner = make_solver(pname, cfg, pscope)
                self.preconditioner._owns_scaling = False
        self._jit_cache: Dict[Any, Any] = {}
        self.setup_time = 0.0

    # -- norm ------------------------------------------------------------
    def _norm(self, v, axis_name=None, num_owned=None):
        bs = self.A.block_dimx if self.A is not None else 1
        return blas.norm(v, self.norm_type, block_size=bs,
                         use_scalar_norm=self.use_scalar_norm,
                         axis_name=axis_name, num_owned=num_owned)

    # -- setup -----------------------------------------------------------
    def setup(self, A: CsrMatrix):
        """Build solver state for matrix A (Solver::setup analog)."""
        return self._setup_impl(A, reuse=False)

    def resetup(self, A: CsrMatrix):
        """Rebuild coefficients keeping structure where possible
        (AMGX_solver_resetup analog). Mirrors setup but routes into
        solver_resetup so subsystems with reusable structure (AMG with
        structure_reuse_levels) can keep it."""
        return self._setup_impl(A, reuse=True)

    def setup_async(self, A: CsrMatrix):
        """Run setup on a worker thread (AsyncSolverSetupTask analog,
        include/amg_level.h:25-39); returns a task whose wait() joins
        and re-raises. The solver must not be used before wait()."""
        from ..thread_manager import setup_async
        return setup_async(self, A)

    def _setup_impl(self, A: CsrMatrix, reuse: bool):
        from ..profiling import trace_region
        # two literal span names (not one computed string) so the
        # static registry check (tools/check_spans.py) covers them
        if reuse:
            with trace_region(f"{self.name}.resetup"):
                out = self.__setup_impl(A, reuse)
        else:
            with trace_region(f"{self.name}.setup"):
                out = self.__setup_impl(A, reuse)
        if self.telemetry:
            from ..memory_info import peak_bytes
            from ..telemetry import metrics as _tm
            _tm.max_gauge("memory.setup_peak_bytes", peak_bytes())
        return out

    def __setup_impl(self, A: CsrMatrix, reuse: bool):
        t0 = time.perf_counter()
        snap = self._resetup_debug_snapshot() if reuse else None
        if not A.initialized:
            A = A.init()
        if self._owns_scaling and self.scaling not in ("NONE", ""):
            # scale the equations before the tree is built; the whole
            # solver (incl. nested preconditioners) then works on L A R
            # (Solver::setup scaler path, src/solvers/solver.cu:465-476)
            from ..scalers import make_scaler
            self.scaler = make_scaler(self.scaling, self.cfg, self.scope)
            self.scaler.setup(A)
            A = self.scaler.scale_matrix(A)
            if not A.initialized:
                A = A.init()
        self.A = A
        # preconditioner first: solvers whose setup probes the
        # preconditioned operator (e.g. Chebyshev eigen-estimation) need it
        if self.preconditioner is not None:
            (self.preconditioner.resetup if reuse
             else self.preconditioner.setup)(self.precond_operator(A))
        (self.solver_resetup if reuse else self.solver_setup)()
        # a value-only resetup changes no static solve state (shapes,
        # level counts, color counts all derive from the structure,
        # which is kept) — the traced solve functions stay valid and
        # the new coefficients flow through as arguments; clearing
        # would force a full Python re-trace per coefficient cycle
        if not (reuse and self._resetup_kept_static()):
            self._jit_cache.clear()
            # batched wrappers close over this tree's traces, so they
            # go stale together (same-structure replays would serve
            # stale baked constants — Chebyshev spectra, color counts).
            # A wrapper suppresses this during its own multi-matrix
            # resetup loop, where structure reuse is enforced and
            # trace-baking solvers are rejected (batch/core.py).
            for b in tuple(getattr(self, "_batched_wrappers", ())):
                if not b._suppress_invalidation:
                    b._jit_cache.clear()
        elif snap is not None:
            self._assert_resetup_contract(snap)
        self.setup_time = time.perf_counter() - t0
        return self

    def _resetup_kept_static(self) -> bool:
        """Did the last resetup keep every static ingredient of this
        (sub)tree's traced solve functions? Standard solvers' static
        state derives from the matrix PATTERN (shapes, colorings, ELL
        widths), which replace_coefficients keeps by contract — so the
        default is True and the question recurses down the chain. The
        AMG wrapper overrides: its hierarchy depth/level shapes depend
        on the VALUES unless the fused value-only resetup ran.

        CONTRACT (load-bearing for resetup trace reuse AND for the
        batched subsystem's per-system value splice, batch/core.py):
        when this returns True after a resetup, the cached jitted solve
        functions are replayed with the NEW solve_data() as arguments —
        so every value-derived quantity `solve_iteration` reads must
        flow through `solve_data()` leaves. A solver that bakes
        value-derived Python scalars into its trace (CHEBYSHEV's _d/_c)
        must override this to return False, or the replayed trace serves
        stale coefficients. Debug builds verify the observable half of
        the contract (set AMGX_TPU_DEBUG_RESETUP=1): solve_data's pytree
        structure/shapes/dtypes must survive a static-kept resetup
        unchanged, and new coefficients must surface as new leaves."""
        return (self.preconditioner is None
                or self.preconditioner._resetup_kept_static())

    # -- resetup contract checking (AMGX_TPU_DEBUG_RESETUP=1) ------------
    @staticmethod
    def _debug_resetup_enabled() -> bool:
        return os.environ.get("AMGX_TPU_DEBUG_RESETUP", "0") not in (
            "", "0", "false", "False")

    def _resetup_debug_snapshot(self):
        """Pre-resetup snapshot of the solve_data pytree (debug mode
        only): treedef + per-leaf (shape, dtype) + leaf ids + the old
        coefficient array's id."""
        if not self._debug_resetup_enabled() or self.A is None:
            return None
        leaves, treedef = jax.tree_util.tree_flatten(self.solve_data())
        # the snapshot RETAINS the leaf objects (not just their ids):
        # holding them alive is what makes the post-resetup id
        # comparison sound — a freed array's address can be reused by a
        # new allocation, which would both mask real violations and
        # fire spurious ones
        return {
            "treedef": treedef,
            "shapes": [(getattr(l, "shape", None),
                        str(getattr(l, "dtype", ""))) for l in leaves],
            "leaves": leaves,
            "values": self.A.values,
        }

    def _assert_resetup_contract(self, snap):
        """After a resetup that kept the traced solves (jit cache NOT
        cleared), the new solve_data must be a drop-in argument for the
        cached traces: identical treedef and per-leaf shapes/dtypes.
        Additionally, if the coefficients changed, at least one leaf
        must be a NEW array — an id-identical leaf set means the new
        values never reached solve_data and the replayed trace would
        serve stale coefficients (the failure mode the
        _resetup_kept_static contract exists to prevent)."""
        leaves, treedef = jax.tree_util.tree_flatten(self.solve_data())
        if treedef != snap["treedef"]:
            raise AssertionError(
                f"solver {self.name}: resetup kept the traced solves but "
                f"changed the solve_data pytree structure")
        shapes = [(getattr(l, "shape", None),
                   str(getattr(l, "dtype", ""))) for l in leaves]
        if shapes != snap["shapes"]:
            bad = [i for i, (a, b) in enumerate(zip(shapes,
                                                    snap["shapes"]))
                   if a != b][:5]
            raise AssertionError(
                f"solver {self.name}: resetup kept the traced solves but "
                f"changed solve_data leaf shapes/dtypes at flat indices "
                f"{bad}")
        if self.A.values is not snap["values"] and \
                {id(l) for l in leaves} == {id(l) for l in snap["leaves"]}:
            raise AssertionError(
                f"solver {self.name}: coefficients changed on resetup "
                f"but every solve_data leaf is the pre-resetup object — "
                f"value-derived state is not flowing through solve_data")

    def precond_operator(self, A: CsrMatrix) -> CsrMatrix:
        """The operator the preconditioner tree is set up against
        (REFINEMENT overrides this with the reduced-precision cast)."""
        return A

    def solver_setup(self):
        """Build solver-specific state for self.A.

        _resetup_kept_static contract: anything computed here from the
        matrix VALUES (diagonal inverses, factors, eigen estimates) that
        the solve phase reads must be stored so `solve_data()` exposes it
        as a pytree leaf — a value-only resetup then reruns this method
        and the refreshed leaves flow into the CACHED jitted solve as
        arguments. Value-derived state kept as Python scalars (baked
        into the trace as constants) breaks that replay; such solvers
        must override `_resetup_kept_static` to return False."""
        pass

    def solver_resetup(self):
        self.solver_setup()

    # -- functional pieces (pure, jittable) ------------------------------
    def solve_data(self) -> Dict[str, Any]:
        """The pytree of device data the jitted solve needs. Includes the
        preconditioner's data under 'precond'. Solvers whose iterations
        only SpMV against A (slim_A_ok) pass a layout-only view so
        unused CSR payloads stay out of the solve program's HBM."""
        A = self.A
        if self.slim_A_ok and hasattr(A, "slim_for_spmv"):
            A = A.slim_for_spmv()
        d: Dict[str, Any] = {"A": A}
        if self.preconditioner is not None:
            d["precond"] = self.preconditioner.solve_data()
        return d

    def solve_init(self, data, b, x, r) -> Dict[str, Any]:
        """Extra solver state (beyond x/r) before the first iteration."""
        return {}

    def _guard_init(self) -> Dict[str, Any]:
        """Initial breakdown flag for the health guards: solvers that
        classify recurrence breakdowns set state['breakdown'] each
        iteration and the driver folds it into SolveStatus, exiting
        the loop cleanly instead of propagating NaNs. The key exists
        only when guards are on, so the guard-off trace carries no
        dead state. Call from solve_init and merge into the state."""
        return {"breakdown": jnp.asarray(False)} if self.health_guards \
            else {}

    def solve_iteration(self, data, b, state) -> Dict[str, Any]:
        """One iteration as a pure function of (data, b, state).

        _resetup_kept_static contract: read value-derived quantities
        from `data` (the solve_data pytree), never from `self` — self
        attributes trace as compile-time constants, which is only sound
        for PATTERN-derived state (shapes, colorings, sweep counts).
        The iteration must also be `jax.vmap`-compatible (no host
        round-trips, no shape-dependent Python branching on values) —
        the batched subsystem (batch/core.py) maps it over a leading
        system axis."""
        raise NotImplementedError

    def _diag_probe_spec(self):
        """(amg, data_keys) when this solver tree owns an AMG hierarchy
        with convergence diagnostics ON (telemetry/diagnostics.py) —
        `data_keys` is the solve_data path from this tree's root to the
        hierarchy's subtree, so the traced driver can hand the probe
        cycle its data at any preconditioner nesting depth. None when
        the knob is off, the hierarchy is empty (no smoothed levels to
        attribute), or the levels are not plain single-chip AMGLevels
        (sharded hierarchies record per-shard norms that would need a
        psum — the distributed path builds with diag=False anyway)."""
        s, keys = self, []
        for _ in range(8):
            if s is None:
                return None
            amg = getattr(s, "amg", None)
            if amg is not None:
                from ..amg.hierarchy import AMGLevel
                if (getattr(amg, "diagnostics", False) and amg.levels
                        and all(isinstance(lv, AMGLevel)
                                for lv in amg.levels)):
                    return amg, keys + ["amg"]
                return None
            keys.append(s._child_data_key)
            s = s.preconditioner
        return None

    def _extra_stats_spec(self) -> tuple:
        """Names of solver-specific SCALARS appended to the packed
        stats vector, in order (after res_hist, before the diagnostics
        probe tail). Default empty: the packed layout — and therefore
        every traced solve program — is unchanged. REFINEMENT declares
        ("inner_iters",) when the solve_precision policy is active so
        per-precision iteration counts reach SolveReport with zero
        extra device->host transfers (they ride the stats buffer)."""
        return ()

    def _extra_stats(self, final_state) -> tuple:
        """The scalar values matching _extra_stats_spec, read from the
        final while_loop state."""
        return ()

    def _precision_block(self, res) -> Optional[Dict[str, Any]]:
        """SolveReport.precision payload, or None when the
        solve_precision policy is inactive (the bitwise-off default).
        Subclasses with per-precision accounting (REFINEMENT) extend
        the base block with inner-loop counts."""
        pol = getattr(self, "_precision_policy", None)
        if pol is None or not pol.active:
            return None
        return {
            "solve_precision": pol.name,
            "cycle_dtype": pol.cast_dtype or "native",
            "outer_dtype": None if self.A is None else str(self.A.dtype),
            "outer_iterations": int(res.iterations),
        }

    def computes_residual(self) -> bool:
        """True when solve_iteration maintains state['r'] itself; else the
        driver recomputes r = b - Ax for monitoring."""
        return True

    def internal_res_norm(self, state):
        """Optional cheap residual-norm estimate maintained by the solver
        (e.g. GMRES |g[i+1]|). Return None to let the driver compute it."""
        return None

    def finalize(self, data, b, state):
        """Post-loop fixup returning the final x (GMRES reconstructs x
        from the Krylov basis here)."""
        return state["x"]

    def apply(self, data, rhs):
        """Preconditioner action M^{-1} rhs: zero-init solve with a fixed
        number of iterations (no convergence monitoring), fully traced."""
        x0 = jnp.zeros_like(rhs)
        r0 = rhs
        st = {"x": x0, "r": r0}
        st.update(self.solve_init(data, rhs, x0, r0))

        def body(_, s):
            return self.solve_iteration(data, rhs, s)

        st = jax.lax.fori_loop(0, self.max_iters, body, st)
        return st["x"]

    def apply_dot(self, data, rhs):
        """Preconditioner action PLUS the LOCAL x.rhs scalar when the
        application's final kernel can emit it as a free epilogue
        ((x, dot), dot None otherwise — callers then reduce
        explicitly). PCG reads it as r.z: the preconditioner's rhs is
        the residual, so the cycle-borne dot saves the iteration's
        full-vector r.z pass (Krylov shell fusion). Base solvers have
        no epilogue-capable kernel: (apply, None)."""
        return self.apply(data, rhs), None

    # -- the jitted driver ----------------------------------------------
    def _build_solve_fn(self, diag: bool = True):
        """Return the raw (unjitted) solve function; jit happens in
        solve(), and the distributed layer shard_maps it instead.

        Health guards (resilience/): the convergence check folds NaN
        detection, breakdown classification, divergence and stall
        detection into ONE int32 `status` carried in the while_loop
        state — everything derives from the residual norm the monitor
        already computed (plus the solver-maintained `breakdown` flag),
        so guarded solves add no device->host synchronization per
        iteration.

        Convergence diagnostics (telemetry/diagnostics.py): with the
        `diagnostics=1` knob on an AMG member of the tree, ONE
        instrumented probe cycle on the final residual is appended to
        the traced program and its per-level stage norms ride the SAME
        packed stats vector — no extra output buffers, no extra
        transfers. `diag=False` opts a consumer out (the batched vmap
        and shard_map wrappers, and REFINEMENT's inner fn, whose stats
        unpacking assumes the bare layout); with the knob off the
        emitted jaxpr is identical either way."""
        diag_spec = self._diag_probe_spec() if diag else None
        max_iters = self.max_iters
        monitor = self.monitor_residual
        hist_len = max_iters + 1
        div_tol = self.rel_div_tolerance
        conv = self.convergence
        guards = self.health_guards
        stall_w = self.stall_window if guards else 0
        stall_tol = self.stall_tolerance
        S = SolveStatus

        def solve_fn(data, b, x0):
            A = data["A"]
            r0 = _residual(A, x0, b)
            norm0 = self._norm(r0)
            state = {"x": x0, "r": r0}
            state.update(self.solve_init(data, b, x0, r0))
            state["iters"] = jnp.asarray(0, jnp.int32)
            # zero RHS / zero initial residual: x0 solves the system
            # exactly — CONVERGED at 0 iterations instead of feeding
            # norm0 == 0 into the relative-tolerance arithmetic
            zero0 = jnp.all(norm0 == 0)
            conv0 = conv.check(norm0, norm0) if monitor \
                else jnp.asarray(False)
            done0 = conv0 | zero0
            state["done"] = done0
            state["converged"] = done0
            state["status"] = jnp.where(done0, jnp.int32(S.CONVERGED),
                                        jnp.int32(_ST_RUNNING))
            state["res_norm"] = norm0
            state["res_hist"] = jnp.zeros(
                (hist_len,) + np.shape(norm0), norm0.dtype
            ).at[0].set(norm0)

            def cond(st):
                return (~st["done"]) & (st["iters"] < max_iters)

            def body(st):
                iters = st["iters"]
                core = {k: v for k, v in st.items()
                        if k not in ("iters", "done", "converged",
                                     "res_norm", "res_hist", "status")}
                with _fi.iteration_scope(iters):
                    core = self.solve_iteration(data, b, core)
                new = dict(st)
                new.update(core)
                new["iters"] = iters + 1
                if monitor:
                    rn_int = self.internal_res_norm(core)
                    if rn_int is not None:
                        # internal estimates (GMRES |g[i+1]|) are scalar;
                        # broadcast to the monitored norm's shape (block
                        # norms are per-component vectors)
                        rn = jnp.broadcast_to(jnp.asarray(rn_int),
                                              np.shape(norm0))
                    elif self.computes_residual():
                        rn = self._norm(core["r"])
                    else:
                        rn = self._norm(_residual(A, core["x"], b))
                    new["res_norm"] = rn
                    new["res_hist"] = st["res_hist"].at[iters + 1].set(rn)
                    cvg = conv.check(rn, norm0)
                    false_ = jnp.asarray(False)
                    diverged = false_
                    if div_tol > 0:
                        diverged = jnp.any(rn > div_tol * norm0)
                    bad = ~jnp.all(jnp.isfinite(rn)) if guards else false_
                    brk = core.get("breakdown", false_) if guards \
                        else false_
                    stalled = false_
                    if stall_w > 0:
                        # sliding window over the history already being
                        # recorded: stalled when the norm failed to drop
                        # by stall_tolerance over the last stall_w steps
                        past = jax.lax.dynamic_index_in_dim(
                            new["res_hist"],
                            jnp.maximum(iters + 1 - stall_w, 0),
                            axis=0, keepdims=False)
                        stalled = (iters + 1 >= stall_w) & jnp.all(
                            rn >= (1.0 - stall_tol) * past)
                    # first terminal condition wins; convergence beats
                    # the failure classes (an exactly-converged CG also
                    # trips p.Ap == 0). BREAKDOWN outranks NAN: the
                    # Krylov breakdown flags are NaN-comparison-False
                    # under a NaN storm (so NaN storms still classify
                    # NAN_DETECTED), while AMG's non-finite-cycle flag
                    # must not be drowned by the NaN its own breakdown
                    # put into the residual
                    status_now = jnp.where(
                        cvg, jnp.int32(S.CONVERGED),
                        jnp.where(brk, jnp.int32(S.BREAKDOWN),
                        jnp.where(bad, jnp.int32(S.NAN_DETECTED),
                        jnp.where(diverged, jnp.int32(S.DIVERGED),
                        jnp.where(stalled, jnp.int32(S.STALLED),
                                  jnp.int32(_ST_RUNNING))))))
                    new["status"] = jnp.where(
                        st["status"] == _ST_RUNNING, status_now,
                        st["status"])
                    new["converged"] = \
                        new["status"] == jnp.int32(S.CONVERGED)
                    new["done"] = new["status"] != jnp.int32(_ST_RUNNING)
                return new

            final = jax.lax.while_loop(cond, body, state)
            if _fi.any_loop_fault_armed():
                # one poisoned trace per armed firing: the retry after a
                # transient fault compiles clean (epoch is in the jit
                # cache keys)
                _fi.consume_loop_faults()
            x_final = self.finalize(data, b, final)
            status = jnp.where(final["status"] == _ST_RUNNING,
                               jnp.int32(S.MAX_ITERS), final["status"])
            # pack every scalar/stat output into ONE auxiliary array:
            # remote/tunneled TPU rigs pay a full round trip PER awaited
            # output buffer, so (x, stats) costs two concurrent awaits
            # where six separate outputs cost six serialized ones
            # at least f32 so iteration counts survive the cast exactly
            # even for bf16/f16 solves
            rdt = jnp.promote_types(jnp.asarray(norm0).dtype, jnp.float32)
            pieces = [
                jnp.reshape(final["iters"].astype(rdt), (1,)),
                jnp.reshape(final["converged"].astype(rdt), (1,)),
                jnp.reshape(status.astype(rdt), (1,)),
                jnp.ravel(jnp.asarray(norm0)),
                jnp.ravel(jnp.asarray(final["res_norm"])),
                jnp.ravel(jnp.asarray(final["res_hist"]))]
            # solver-declared extra scalars (e.g. REFINEMENT's inner
            # iteration count under an active solve_precision policy)
            # ride the same packed buffer — zero added transfers; the
            # spec is empty by default so the layout is unchanged.
            # Gated on `diag` exactly like the probe tail: the batched
            # / distributed / inner-fn consumers (diag=False) unpack
            # the BARE stats layout
            if diag:
                for v in self._extra_stats(final):
                    pieces.append(jnp.reshape(
                        jnp.asarray(v).astype(rdt), (1,)))
            if diag_spec is not None:
                # diagnostics probe: one instrumented cycle on the
                # residual equation A d = r_final, appended INSIDE the
                # traced program; its stage norms pack onto the stats
                # tail (_solve_traced strips them by the same spec)
                from ..telemetry import diagnostics as _dg
                amg_, keys_ = diag_spec
                sub = data
                for k_ in keys_:
                    sub = sub[k_]
                r_fin = _residual(A, x_final, b)
                pieces.append(jnp.ravel(
                    _dg.probe_cycle(amg_, sub, r_fin, rdt)))
            stats = jnp.concatenate(pieces)
            return x_final, stats

        return solve_fn

    # -- chunked stepping (serving/engine.py continuous batching) --------
    def _build_chunk_fns(self, chunk: int):
        """Resumable chunked-iteration solve entry — the substrate of the
        serving layer's continuous batching (serving/engine.py). Returns
        three pure, jittable, vmap-compatible functions::

            init_fn(data, b, x0)      -> state
            step_fn(data, b, state)   -> state   # <= `chunk` more iters
            finish_fn(data, b, state) -> (x, stats)

        The state is the SAME recurrence `_build_solve_fn`'s while_loop
        carries, with `norm0` carried as an explicit state leaf so
        stepping can resume across host boundaries: a system stepped in
        chunks visits bit-identical iterates to a one-shot solve, and a
        converged/terminal system's state is frozen by the loop
        predicate — so a drained batch slot costs nothing while its
        neighbors finish, and the scheduler can refill it at the next
        cycle boundary instead of waiting for the whole batch. The
        chunk window is per-system relative (`iters < entry_iters +
        chunk`), so freshly admitted systems and veterans advance the
        same number of iterations per engine cycle. `finish_fn` packs
        the identical stats vector `unpack_stats` inverts."""
        max_iters = self.max_iters
        monitor = self.monitor_residual
        hist_len = max_iters + 1
        div_tol = self.rel_div_tolerance
        conv = self.convergence
        guards = self.health_guards
        stall_w = self.stall_window if guards else 0
        stall_tol = self.stall_tolerance
        S = SolveStatus
        chunk = int(chunk)

        def init_fn(data, b, x0):
            A = data["A"]
            r0 = _residual(A, x0, b)
            norm0 = self._norm(r0)
            state = {"x": x0, "r": r0}
            state.update(self.solve_init(data, b, x0, r0))
            state["iters"] = jnp.asarray(0, jnp.int32)
            zero0 = jnp.all(norm0 == 0)
            conv0 = conv.check(norm0, norm0) if monitor \
                else jnp.asarray(False)
            done0 = conv0 | zero0
            state["done"] = done0
            state["converged"] = done0
            state["status"] = jnp.where(done0, jnp.int32(S.CONVERGED),
                                        jnp.int32(_ST_RUNNING))
            state["res_norm"] = norm0
            state["norm0"] = norm0
            state["res_hist"] = jnp.zeros(
                (hist_len,) + np.shape(norm0), norm0.dtype
            ).at[0].set(norm0)
            return state

        # mirror of _build_solve_fn's loop body, reading norm0 from the
        # carried state instead of a closure (bit-identical per-system
        # iterates is the chunked/one-shot parity contract test_serving
        # checks)
        def body(data, b, st):
            norm0 = st["norm0"]
            iters = st["iters"]
            core = {k: v for k, v in st.items()
                    if k not in ("iters", "done", "converged",
                                 "res_norm", "res_hist", "status",
                                 "norm0")}
            with _fi.iteration_scope(iters):
                core = self.solve_iteration(data, b, core)
            new = dict(st)
            new.update(core)
            new["iters"] = iters + 1
            if monitor:
                rn_int = self.internal_res_norm(core)
                if rn_int is not None:
                    rn = jnp.broadcast_to(jnp.asarray(rn_int),
                                          np.shape(norm0))
                elif self.computes_residual():
                    rn = self._norm(core["r"])
                else:
                    rn = self._norm(_residual(data["A"], core["x"], b))
                new["res_norm"] = rn
                new["res_hist"] = st["res_hist"].at[iters + 1].set(rn)
                cvg = conv.check(rn, norm0)
                false_ = jnp.asarray(False)
                diverged = false_
                if div_tol > 0:
                    diverged = jnp.any(rn > div_tol * norm0)
                bad = ~jnp.all(jnp.isfinite(rn)) if guards else false_
                brk = core.get("breakdown", false_) if guards \
                    else false_
                stalled = false_
                if stall_w > 0:
                    past = jax.lax.dynamic_index_in_dim(
                        new["res_hist"],
                        jnp.maximum(iters + 1 - stall_w, 0),
                        axis=0, keepdims=False)
                    stalled = (iters + 1 >= stall_w) & jnp.all(
                        rn >= (1.0 - stall_tol) * past)
                status_now = jnp.where(
                    cvg, jnp.int32(S.CONVERGED),
                    jnp.where(brk, jnp.int32(S.BREAKDOWN),
                    jnp.where(bad, jnp.int32(S.NAN_DETECTED),
                    jnp.where(diverged, jnp.int32(S.DIVERGED),
                    jnp.where(stalled, jnp.int32(S.STALLED),
                              jnp.int32(_ST_RUNNING))))))
                new["status"] = jnp.where(
                    st["status"] == _ST_RUNNING, status_now,
                    st["status"])
                new["converged"] = \
                    new["status"] == jnp.int32(S.CONVERGED)
                new["done"] = new["status"] != jnp.int32(_ST_RUNNING)
            return new

        def step_fn(data, b, state):
            entry = state["iters"]

            def cond(st):
                return ((~st["done"]) & (st["iters"] < max_iters)
                        & (st["iters"] < entry + chunk))

            out = jax.lax.while_loop(
                cond, lambda st: body(data, b, st), state)
            if _fi.any_loop_fault_armed():
                _fi.consume_loop_faults()
            return out

        def finish_fn(data, b, state):
            norm0 = state["norm0"]
            x_final = self.finalize(data, b, state)
            status = jnp.where(state["status"] == _ST_RUNNING,
                               jnp.int32(S.MAX_ITERS), state["status"])
            rdt = jnp.promote_types(jnp.asarray(norm0).dtype,
                                    jnp.float32)
            stats = jnp.concatenate([
                jnp.reshape(state["iters"].astype(rdt), (1,)),
                jnp.reshape(state["converged"].astype(rdt), (1,)),
                jnp.reshape(status.astype(rdt), (1,)),
                jnp.ravel(jnp.asarray(norm0)),
                jnp.ravel(jnp.asarray(state["res_norm"])),
                jnp.ravel(jnp.asarray(state["res_hist"]))])
            return x_final, stats

        return init_fn, step_fn, finish_fn

    @staticmethod
    def unpack_stats(stats, hist_len: int):
        """Invert the stats packing of _build_solve_fn: returns
        (iters, converged, status, norm0, res_norm, res_hist) as numpy
        values. The norm width (1, or block_size for per-component block
        norms) is recovered from the packed length. res_hist is trimmed
        to the actual iteration count (iters + 1 entries), so the
        post-exit zero padding of the fixed-length history buffer never
        reaches callers or plots."""
        stats = np.asarray(stats)
        nb = (stats.size - 3) // (2 + hist_len)
        iters = int(stats[0])
        converged = bool(stats[1])
        status = int(stats[2])
        norm0 = stats[3:3 + nb]
        res_norm = stats[3 + nb:3 + 2 * nb]
        hist = stats[3 + 2 * nb:].reshape(hist_len, nb)[: iters + 1]
        if nb == 1:
            norm0, res_norm, hist = norm0[0], res_norm[0], hist[:, 0]
        return iters, converged, status, norm0, res_norm, hist

    def solve(self, b, x0=None, zero_initial_guess: bool = False
              ) -> SolveResult:
        """Solve A x = b (Solver::solve analog, include/solvers/solver.h)."""
        from ..profiling import trace_region
        with trace_region(f"{self.name}.solve"):
            return self._solve_traced(b, x0, zero_initial_guess)

    def _solve_traced(self, b, x0=None, zero_initial_guess: bool = False
                      ) -> SolveResult:
        if self.A is None:
            raise BadParametersError(
                f"solver {self.name}: solve() before setup()")
        b = jnp.asarray(b)
        if x0 is None or zero_initial_guess:
            x0 = jnp.zeros_like(b)
        else:
            x0 = jnp.asarray(x0)
        if self.scaler is not None:
            # solve (LAR) x' = L b, return x = R x' (monitored residuals
            # are in the scaled system — reference caveat solver.cu:449)
            b = self.scaler.scale_rhs(b)
            x0 = self.scaler.to_scaled_x(x0)
        # the faultinject epoch keys the cache so arming/consuming a
        # fault retraces instead of replaying a (possibly poisoned)
        # cached program; it is 0 forever when injection is unused
        key = (b.shape, str(b.dtype), _fi.epoch())
        if key not in self._jit_cache:
            from ..telemetry import metrics as _tm
            _tm.inc("solver.retrace.solve")
            _fi.evict_stale_epochs(self._jit_cache, key[-1])
            self._jit_cache[key] = jax.jit(self._build_solve_fn())
        t0 = time.perf_counter()
        x, stats = jax.block_until_ready(self._jit_cache[key](
            self.solve_data(), b, x0))
        if self.scaler is not None:
            x = self.scaler.from_scaled_x(x)
        solve_time = time.perf_counter() - t0
        # diagnostics probe output rides the stats tail (same buffer,
        # no extra transfer); strip it by the same spec the trace used
        # before the bare-layout unpack
        diag_spec = self._diag_probe_spec()
        diag_raw = None
        stats = np.asarray(stats)
        if diag_spec is not None:
            from ..telemetry import diagnostics as _dg
            dlen = _dg.slots_len(diag_spec[0])
            if dlen:
                diag_raw = stats[stats.size - dlen:]
                stats = stats[:stats.size - dlen]
        # solver-declared extras sit just before the diagnostics tail;
        # strip by the same spec the trace packed them with
        extra_names = self._extra_stats_spec()
        extras = None
        if extra_names:
            raw = stats[stats.size - len(extra_names):]
            stats = stats[:stats.size - len(extra_names)]
            extras = {k: float(v) for k, v in zip(extra_names, raw)}
        iters_i, converged, status, norm0, res_norm, hist = \
            self.unpack_stats(stats, self.max_iters + 1)
        res = SolveResult(
            x=x, iterations=iters_i, converged=converged,
            res_norm=np.asarray(res_norm), norm0=np.asarray(norm0),
            res_history=np.asarray(hist)
            if self.store_res_history else None,
            setup_time=self.setup_time, solve_time=solve_time,
            status_code=status, extra_stats=extras)
        if self.telemetry:
            # structured report (telemetry/report.py): built from the
            # stats numpy already unpacked above + static hierarchy
            # metadata — no device data is touched
            from ..memory_info import peak_bytes
            from ..telemetry import build_report, metrics as _tm
            diag_struct = None
            if diag_raw is not None:
                from ..telemetry import diagnostics as _dg
                diag_struct = _dg.derive(
                    diag_raw, len(diag_spec[0].levels),
                    res_hist=np.asarray(hist))
            res.report = build_report(self, res, hist=np.asarray(hist),
                                      diagnostics=diag_struct,
                                      precision=self._precision_block(res))
            _tm.max_gauge("memory.solve_peak_bytes", peak_bytes())
        if self.print_solve_stats:
            self._print_stats(res, np.asarray(hist))
        return res

    def _print_stats(self, res: SolveResult, hist):
        from ..memory_info import update_max_memory_usage
        mem_gb = update_max_memory_usage() / 2**30
        amgx_printf(f"    iter      Mem Usage (GB)       residual           rate")
        amgx_printf(f"    {'-' * 62}")
        for i in range(res.iterations + 1):
            rate = ""
            if i > 0 and np.all(hist[i - 1] > 0):
                rate = f"{float(np.max(hist[i] / hist[i - 1])):14.4f}"
            tag = "Ini" if i == 0 else f"{i - 1:4d}"
            amgx_printf(f"    {tag}         {mem_gb:10.4f}      "
                  f"{float(np.max(hist[i])):14.6e} {rate}")
        amgx_printf(f"    {'-' * 62}")
        status = res.status if not res.converged else "success"
        amgx_printf(f"    Total Iterations: {res.iterations}")
        amgx_printf(f"    Avg Convergence Rate: "
              f"{float((np.max(hist[res.iterations]) / max(np.max(hist[0]), 1e-300)) ** (1.0 / max(res.iterations, 1))):10.4f}")
        amgx_printf(f"    Final Residual: {float(np.max(res.res_norm)):.6e}")
        amgx_printf(f"    Solve Status: {status}")
        if self.obtain_timings:
            amgx_printf(f"    Setup Time: {res.setup_time:.4f}s")
            amgx_printf(f"    Solve Time: {res.solve_time:.4f}s")

    # -- batched solves ---------------------------------------------------
    def solve_many(self, bs, matrices=None, x0s=None,
                   zero_initial_guess: bool = False):
        """Solve many systems in ONE jitted program (batch/core.py):
        `bs` stacks the right-hand sides along a leading batch axis.
        With matrices=None this is multi-RHS against the set-up matrix;
        with a list of same-pattern matrices each system gets its own
        coefficients (hierarchy structure reused, values spliced via the
        resetup path). Returns a BatchedSolveResult. The wrapped batched
        state is cached on the solver, so repeat calls with the same
        batch geometry reuse one trace."""
        if getattr(self, "_batched", None) is None:
            from ..batch import BatchedSolver
            self._batched = BatchedSolver(solver=self)
        return self._batched.solve_many(
            bs, matrices=matrices, x0s=x0s,
            zero_initial_guess=zero_initial_guess)

    # -- smoother interface (AMG levels) ---------------------------------
    def smooth(self, data, b, x, sweeps: int):
        """Apply `sweeps` relaxation sweeps to x (pure function). Default:
        run solve_iteration with monitoring off."""
        st = {"x": x, "r": _residual(data["A"], x, b)}
        st.update(self.solve_init(data, b, x, st["r"]))

        def body(_, s):
            return self.solve_iteration(data, b, s)

        st = jax.lax.fori_loop(0, sweeps, body, st)
        return st["x"]

    def smooth_residual(self, data, b, x, sweeps: int):
        """(x', r) after `sweeps` smoothing sweeps plus the residual
        r = b - A x' — the V-cycle's presmooth->restrict hot pair
        (amg/cycles.py). The default composes smooth() with one extra
        SpMV, so every smoother keeps working; the damped-relaxation
        smoothers (relaxation.py, polynomial.py) override with the
        fused single-pass kernels (ops/smooth.py) when the level's
        layout supports them."""
        x = self.smooth(data, b, x, sweeps)
        return x, _residual(data["A"], x, b)


def make_solver(name: str, cfg: Config, scope: str = "default") -> Solver:
    """SolverFactory::allocate analog."""
    cls = registry.solvers.get(name)
    return cls(cfg, scope, name=name.upper())
