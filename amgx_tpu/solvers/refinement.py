"""Mixed-precision defect correction (iterative refinement).

TPU-native execution strategy for full double-precision (dDDI-mode)
accuracy: the TPU has no native f64 datapath — bulk f64 vector work runs
~10x slower than f32 — so solving the whole system in f64 wastes the
machine. REFINEMENT runs the classic defect-correction loop instead
(the same scheme LAPACK dsgesv uses around an f32 LU, and the standard
mixed-precision practice in modern GPU/TPU HPC):

    r_k = b - A x_k                  (f64: one SpMV + axpy per step)
    solve  A32 d = r_k  to tol_inner (f32: any configured inner solver,
                                      e.g. FGMRES + GEO-aggregation AMG)
    x_{k+1} = x_k + d                (f64)

All heavy work (the inner Krylov loop, the AMG cycle) runs in f32 at
full vector-unit speed; the f64 cost is two fused streaming passes per
outer step. Convergence is monitored on the TRUE f64 residual, so the
reported tolerance is meaningful to 1e-14-level — unlike a pure-f32
(dFFI-mode) solve whose estimated residual drifts from the true one
near f32 epsilon.

The inner solver comes from the `preconditioner` role, matching the
nested-solver architecture of the reference (any solver can own a child
solver, src/core.cu:381-388):

    solver=REFINEMENT, tolerance=1e-10, preconditioner(in)=FGMRES,
    in:tolerance=1e-6, in:preconditioner(amg)=AMG, ...

With `solve_precision=bfloat16` this loop is the f64-RESTORING outer
shell of the mixed-precision fused path: the AMG cycle below streams
bf16 operand slabs (f32 in-kernel accumulation, ops/pallas_spmv.py),
the inner Krylov stays f32 (a bf16 Krylov basis would not converge —
flexible Krylov tolerates the reduced-precision preconditioner), and
the outer f64 defect still drives convergence to the requested
tolerance. When the policy is active the driver also accumulates the
INNER iteration count in the while_loop state and packs it onto the
stats vector (zero extra transfers), so `SolveReport.precision`
records per-precision iteration counts — the accuracy/work trade is
measured, not folklore. Unset solve_precision is bitwise-off: no
extra state leaf, jaxpr-identical to the pre-knob build.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import registry
from ..errors import BadParametersError
from ..ops.spmv import residual
from .base import Solver


@registry.solvers.register("REFINEMENT")
@registry.solvers.register("DEFECT_CORRECTION")
class RefinementSolver(Solver):
    """Outer f64 defect-correction loop around an f32 inner solve."""

    is_smoother = False
    uses_preconditioner = True
    inner_dtype = jnp.float32
    # solve_data stores the child tree under "inner" (not the base's
    # "precond") — the diagnostics probe walks this key
    _child_data_key = "inner"

    def precond_operator(self, A):
        # the inner chain (and its own preconditioner tree, e.g. the AMG
        # hierarchy) builds against the reduced-precision operator
        self._A32 = A.astype(self.inner_dtype)
        return self._A32

    def solver_setup(self):
        if self.preconditioner is None:
            raise BadParametersError(
                "REFINEMENT needs an inner solver in the `preconditioner` "
                "role (e.g. preconditioner(in)=FGMRES)")
        # diag=False: the inner fn's stats are discarded each outer step
        # (only d matters); the diagnostics probe belongs to the OUTER
        # driver, which walks the tree to the AMG itself
        self._inner_fn = self.preconditioner._build_solve_fn(diag=False)

    def solve_data(self):
        # overrides the base: the inner data is the f32 solve tree; the
        # outer operator is only ever SpMV'd (defect computation), so a
        # layout-only view suffices
        return {"A": self.A.slim_for_spmv(),
                "inner": self.preconditioner.solve_data()}

    def computes_residual(self):
        return True

    def solve_init(self, data, b, x0, r0):
        st = super().solve_init(data, b, x0, r0)
        if self._precision_policy.active:
            # per-precision accounting: the accumulated inner-Krylov
            # iteration count rides the state (and, via _extra_stats,
            # the packed stats vector). Keyed on the policy so the
            # default build carries no extra leaf (bitwise-off)
            st["inner_iters"] = jnp.zeros((), jnp.float32)
        return st

    def solve_iteration(self, data, b, st):
        x = st["x"]
        r = st["r"]        # f64 defect (maintained by the previous step)
        r32 = r.astype(self.inner_dtype)
        d32, istats = self._inner_fn(data["inner"], r32,
                                     jnp.zeros_like(r32))
        x = x + d32.astype(x.dtype)
        out = dict(st)
        out["x"] = x
        out["r"] = residual(data["A"], x, b)             # true f64 residual
        if "inner_iters" in st:
            # istats[0] is the inner fn's iteration count (the packed
            # stats layout _build_solve_fn emits)
            out["inner_iters"] = st["inner_iters"] + \
                istats[0].astype(jnp.float32)
        return out

    # -- per-precision accounting (solve_precision policy) --------------
    def _extra_stats_spec(self):
        return ("inner_iters",) if self._precision_policy.active else ()

    def _extra_stats(self, final_state):
        if "inner_iters" not in final_state:
            return ()
        return (final_state["inner_iters"],)

    def _precision_block(self, res):
        block = super()._precision_block(res)
        if block is None:
            return None
        block["inner_dtype"] = str(jnp.dtype(self.inner_dtype).name)
        if res.extra_stats is not None:
            block["inner_iterations"] = int(round(
                res.extra_stats.get("inner_iters", 0.0)))
        return block
