"""GMRES / FGMRES with restart.

Analogs of src/solvers/gmres_solver.cu (407 LoC) and fgmres_solver.cu
(585 LoC; the reference's workhorse outer solver). Design notes for the
TPU re-formulation:

- one `solve_iteration` = one Arnoldi step (iteration-count parity with
  the reference, which counts inner steps);
- the Krylov basis V lives as a dense (m+1, n) buffer updated with
  `dynamic_update_slice`; modified-Gram-Schmidt runs as a fori_loop over
  all m rows — rows beyond the current inner index are zero, so their
  projections vanish and no dynamic bounds are needed (static shapes for
  XLA, and the projections are (m+1, n) x (n,) matvecs on the MXU);
- the Hessenberg column is rotated by all m stored Givens rotations
  (identity-initialized, so "not yet created" rotations are no-ops);
- the estimated residual |g[i+1]| drives convergence (exact for the
  true residual in exact arithmetic), so no extra SpMV per step;
- x is reconstructed only at restart boundaries and once after the loop
  (`finalize`), via a masked m x m triangular solve (R is identity-
  initialized, so unused columns solve to y_j = 0).

GMRES applies the preconditioner at reconstruction time (right
preconditioning with a fixed linear M: x = x0 + M (V^T y)); FGMRES stores
the preconditioned vectors Z (flexible: M may vary per step).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl

from .. import registry
from ..ops import blas
from ..ops.spmv import spmv, residual
from .base import Solver


class _GmresBase(Solver):
    uses_preconditioner = True
    flexible = False

    def __init__(self, cfg, scope="default", name="GMRES"):
        super().__init__(cfg, scope, name)
        self.m = int(cfg.get("gmres_n_restart", scope))
        # gmres_krylov_dim caps the stored Krylov basis (reference
        # semantics: 0 = match the restart length)
        kdim = int(cfg.get("gmres_krylov_dim", scope))
        if kdim > 0:
            self.m = min(self.m, kdim)

    def _precond(self, data, r):
        if self.preconditioner is not None:
            return self.preconditioner.apply(data["precond"], r)
        return r

    def computes_residual(self):
        return False

    def internal_res_norm(self, state):
        return state["est_res"]

    # -- state -----------------------------------------------------------
    def solve_init(self, data, b, x, r):
        m, n = self.m, x.shape[0]
        dt = x.dtype
        beta = blas.nrm2(r)
        V = jnp.zeros((m + 1, n), dt).at[0].set(
            r / jnp.where(beta == 0, 1.0, beta))
        st = {
            "x0": x,
            "V": V,
            "R": jnp.eye(m, dtype=dt),
            "cs": jnp.ones((m,), dt),
            "sn": jnp.zeros((m,), dt),
            "g": jnp.zeros((m + 1,), dt).at[0].set(beta),
            "i": jnp.zeros((), jnp.int32),
            "est_res": beta,
        }
        st.update(self._guard_init())
        if self.flexible:
            st["Z"] = jnp.zeros((m, n), dt)
        return st

    # -- helpers ---------------------------------------------------------
    def _y(self, st):
        """Solve the (masked) m x m triangular system R y = g[:m]."""
        return jsl.solve_triangular(st["R"], st["g"][: self.m], lower=False)

    def _reconstruct(self, data, st):
        """x = x0 + correction from the current Krylov data."""
        y = self._y(st)
        if self.flexible:
            corr = st["Z"].T @ y
        else:
            u = st["V"][: self.m].T @ y
            corr = self._precond(data, u)
        return st["x0"] + corr

    def _restart(self, data, b, st, x_new):
        """Reset the cycle state around a new initial guess."""
        m = self.m
        dt = x_new.dtype
        r = residual(data["A"], x_new, b)
        beta = blas.nrm2(r)
        n = x_new.shape[0]
        new = dict(st)
        new["x0"] = x_new
        new["V"] = jnp.zeros((m + 1, n), dt).at[0].set(
            r / jnp.where(beta == 0, 1.0, beta))
        new["R"] = jnp.eye(m, dtype=dt)
        new["cs"] = jnp.ones((m,), dt)
        new["sn"] = jnp.zeros((m,), dt)
        new["g"] = jnp.zeros((m + 1,), dt).at[0].set(beta)
        new["i"] = jnp.zeros((), jnp.int32)
        new["est_res"] = beta
        if self.flexible:
            new["Z"] = jnp.zeros((m, n), dt)
        return new

    # -- one Arnoldi step -------------------------------------------------
    def solve_iteration(self, data, b, st):
        A = data["A"]
        m = self.m
        i = st["i"]
        V = st["V"]
        v_i = V[i]
        z = self._precond(data, v_i)
        if self.flexible:
            Z = jax.lax.dynamic_update_index_in_dim(st["Z"], z, i, 0)
        w = spmv(A, z)

        # classical Gram-Schmidt with reorthogonalization (CGS2) against
        # all rows (zero rows are no-ops): each pass is ONE (m+1, n)
        # matvec pair on the MXU instead of m serialized dot/axpy round
        # trips — the TPU-native reformulation of the reference's MGS
        # loop (fgmres_solver.cu), with CGS2 restoring MGS-level
        # orthogonality. The row-dot matvec finishes with a psum when
        # running inside shard_map (the MPI_Allreduce analog), exactly
        # like blas.dot.
        h = blas.mdot(V, w)
        w = w - V.T @ h
        h2 = blas.mdot(V, w)
        w = w - V.T @ h2
        h = h + h2
        h_last = blas.nrm2(w)
        h = h.at[i + 1].set(h_last)
        V = jax.lax.dynamic_update_index_in_dim(
            V, w / jnp.where(h_last == 0, 1.0, h_last), i + 1, 0)

        # previously stored rotations (identity where not yet created)
        def rot_body(j, h):
            c, s = st["cs"][j], st["sn"][j]
            hj, hj1 = h[j], h[j + 1]
            return h.at[j].set(c * hj + s * hj1).at[j + 1].set(
                -s * hj + c * hj1)

        h = jax.lax.fori_loop(0, m, rot_body, h)

        # new rotation zeroing h[i+1]
        hi = h[i]
        hi1 = h[i + 1]
        denom = jnp.sqrt(hi * hi + hi1 * hi1)
        c = jnp.where(denom == 0, 1.0, hi / jnp.where(denom == 0, 1.0, denom))
        s = jnp.where(denom == 0, 0.0, hi1 / jnp.where(denom == 0, 1.0, denom))
        h = h.at[i].set(c * h[i] + s * h[i + 1]).at[i + 1].set(0.0)
        cs = st["cs"].at[i].set(c)
        sn = st["sn"].at[i].set(s)
        g = st["g"]
        gi = g[i]
        # a degenerate rotation (rotated Hessenberg column entirely
        # zero) reduces nothing: keep |g| at its old magnitude instead
        # of the identity rotation's -s*gi = 0, which would read as
        # instant (false) convergence
        g = g.at[i].set(c * gi).at[i + 1].set(
            jnp.where(denom == 0, gi, -s * gi))
        est = jnp.abs(g[i + 1])

        R = jax.lax.dynamic_update_slice_in_dim(
            st["R"], h[:m][:, None], i, axis=1)

        new = dict(st)
        new.update(V=V, R=R, cs=cs, sn=sn, g=g, est_res=est)
        if self.health_guards:
            # Givens/Hessenberg degeneracy with an unconverged residual:
            # the Arnoldi process produced a zero column — exit cleanly
            new["breakdown"] = (denom == 0) & (jnp.abs(gi) > 0)
        if self.flexible:
            new["Z"] = Z

        # cycle boundary: reconstruct x and restart
        def at_restart(new):
            x_new = self._reconstruct(data, new)
            out = self._restart(data, b, new, x_new)
            out["x"] = x_new
            return out

        def mid_cycle(new):
            out = dict(new)
            out["i"] = new["i"] + 1
            return out

        new["x"] = st["x"]
        return jax.lax.cond(i + 1 >= m, at_restart, mid_cycle, new)

    def finalize(self, data, b, state):
        # mid-cycle exit: reconstruct from the live Krylov data; exactly at
        # a restart boundary i==0 and the reconstruction is x0 itself.
        return jax.lax.cond(
            state["i"] > 0,
            lambda st: self._reconstruct(data, st),
            lambda st: st["x0"],
            state)


@registry.solvers.register("GMRES")
class GMRESSolver(_GmresBase):
    flexible = False


@registry.solvers.register("FGMRES")
class FGMRESSolver(_GmresBase):
    flexible = True
