"""Dense direct solver (coarse-grid solver).

Analog of src/solvers/dense_lu_solver.cu (cuSolverDn getrf/getrs,
:514-580): densify the (small) matrix once at setup, factor it, and
back-substitute per application. XLA:TPU does not implement f64 LU
(see ops/dense.py), so the factorization is Householder QR — same
O(n^3) setup / O(n^2) apply split as getrf/getrs, and the triangular
solve runs on the MXU. The coarsest AMG level is replicated across the
mesh, so this factorization is the `exact_coarse_solve` analog (the
distributed layer all-gathers the coarse rhs before calling this,
mirroring dense_lu_solver.cu:783-930).
"""
from __future__ import annotations

import jax.numpy as jnp
import jax.scipy.linalg as jsl

from .. import registry
from ..ops.spmv import residual
from .base import Solver


@registry.solvers.register("DENSE_LU_SOLVER")
class DenseLUSolver(Solver):
    def __init__(self, cfg, scope="default", name="DENSE_LU_SOLVER"):
        super().__init__(cfg, scope, name)
        self.dense_lu_num_rows = int(cfg.get("dense_lu_num_rows", scope))
        self.dense_lu_max_rows = int(cfg.get("dense_lu_max_rows", scope))
        self.cycle_fusion = bool(int(cfg.get("cycle_fusion", scope)))

    def solver_setup(self):
        dense = self.A.to_dense()
        # guard singular rows (e.g. empty coarse rows) with unit diagonal
        zero_rows = jnp.all(dense == 0, axis=1)
        dense = jnp.where(
            jnp.diag(zero_rows), jnp.eye(dense.shape[0], dtype=dense.dtype),
            dense)
        self._qt, self._r = self._factor(dense)

    @staticmethod
    def _factor(dense):
        q, r = jnp.linalg.qr(dense)
        return q.T, r

    # explicit-inverse size cap for the fused coarse-tail kernel: the
    # padded inverse lives in VMEM during the whole tail sub-cycle
    _TAIL_INV_MAX_ROWS = 1024

    def solve_data(self):
        d = super().solve_data()
        d["qt"] = self._qt
        d["r"] = self._r
        if self.cycle_fusion and self.A is not None \
                and self.A.num_rows <= self._TAIL_INV_MAX_ROWS:
            from ..ops.smooth import fused_runtime_on
            if fused_runtime_on():
                # explicit inverse A^{-1} = R^{-1} Q^T for the
                # VMEM-resident coarse tail (ops/smooth.py): the tail
                # kernel applies the coarsest solve as one MXU matmul.
                # Memoized on the CURRENT factors' identity, so a value
                # resetup that swaps _qt/_r refreshes it while repeated
                # solve_data calls (e.g. hierarchies whose tail never
                # fuses) don't redo the n^2-RHS triangular solve.
                memo = getattr(self, "_inv_memo", None)
                if memo is None or memo[0] is not self._qt \
                        or memo[1] is not self._r:
                    memo = (self._qt, self._r,
                            jsl.solve_triangular(self._r, self._qt,
                                                 lower=False))
                    self._inv_memo = memo
                d["inv"] = memo[2]
        return d

    def _direct(self, data, rhs):
        return jsl.solve_triangular(data["r"], data["qt"] @ rhs, lower=False)

    def solve_iteration(self, data, b, st):
        x = self._direct(data, b)
        out = dict(st)
        out["x"] = x
        out["r"] = residual(data["A"], x, b)
        return out

    def apply(self, data, rhs):
        return self._direct(data, rhs)

    def smooth(self, data, b, x, sweeps):
        return self._direct(data, b)
