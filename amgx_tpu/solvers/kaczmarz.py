"""Kaczmarz smoother.

TPU-native analog of src/solvers/kaczmarz_solver.cu (843 LoC). A
Kaczmarz sweep projects the iterate onto each row's hyperplane:

    x += omega * (b_i - a_i . x) / ||a_i||^2 * a_i^T

The reference ships two flavors selected by `kaczmarz_coloring_needed`
(src/core.cu registry; kaczmarz_solver.cu:494-496): a multicolor sweep
(rows of one color processed in parallel) and a "warp-naive" variant
that simply races the scatters. The TPU redesign keeps the same two
modes but makes both deterministic:

- MC mode: per color, all that color's row projections are applied
  simultaneously with a segment-sum scatter over columns — additive
  collisions between same-color rows that share a column turn the sweep
  into a block-Cimmino update within each color, which is deterministic
  (the reference's racing scatters are not) and convergent for the same
  damping range.
- naive mode (kaczmarz_coloring_needed=0): one simultaneous projection
  over ALL rows (the classical Cimmino iteration) — the deterministic
  analog of the racing warp-naive kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import registry
from ..errors import BadParametersError
from ..ops.coloring import color_matrix
from ..ops.spmv import spmv
from .base import Solver
from .relaxation import safe_recip


@registry.solvers.register("KACZMARZ")
class KaczmarzSolver(Solver):

    is_smoother = True
    slim_A_ok = False      # _project reads COO structure per sweep

    def __init__(self, cfg, scope="default", name="KACZMARZ"):
        super().__init__(cfg, scope, name)
        self.relaxation_factor = float(cfg.get("relaxation_factor", scope))
        self.use_coloring = bool(int(cfg.get("kaczmarz_coloring_needed",
                                             scope)))

    def solver_setup(self):
        A = self.A
        if A.is_block:
            raise BadParametersError("KACZMARZ supports scalar matrices")
        rows, cols, vals = A.coo()
        sq = jax.ops.segment_sum(vals * vals, rows,
                                 num_segments=A.num_rows,
                                 indices_are_sorted=True)
        if A.has_external_diag:
            sq = sq + A.diag * A.diag
        self._inv_rownorm2 = safe_recip(sq)
        if self.use_coloring:
            coloring = color_matrix(A, self.cfg, self.scope)
            self.row_colors = coloring.row_colors
            self.num_colors = int(coloring.num_colors)
        else:
            self.row_colors = jnp.zeros((A.num_rows,), jnp.int32)
            self.num_colors = 1

    def solve_data(self):
        d = super().solve_data()
        d["inv_rn2"] = self._inv_rownorm2
        d["colors"] = self.row_colors
        return d

    def computes_residual(self):
        return False

    def _project(self, data, b, x, mask):
        """Simultaneous damped projection of the masked rows."""
        A = data["A"]
        rows, cols, vals = A.coo()
        r = b - spmv(A, x)
        coef = jnp.where(mask, r * data["inv_rn2"], 0.0)
        # x += omega * avg_i coef_i * a_i^T: scatter over columns; rows
        # of one color that share a column are AVERAGED (convex
        # combination of single-row projections -> non-expansive),
        # instead of the reference's racing scatters
        upd = jax.ops.segment_sum(vals * coef[rows], cols,
                                  num_segments=A.num_cols)
        cnt = jax.ops.segment_sum(
            jnp.where(mask[rows], 1.0, 0.0), cols,
            num_segments=A.num_cols)
        if A.has_external_diag:
            upd = upd.at[jnp.arange(A.num_rows)].add(A.diag * coef)
            cnt = cnt.at[jnp.arange(A.num_rows)].add(
                jnp.where(mask, 1.0, 0.0))
        upd = upd / jnp.maximum(cnt, 1.0)
        return x + self.relaxation_factor * upd[: x.shape[0]]

    def solve_iteration(self, data, b, st):
        x = st["x"]
        if self.num_colors == 1:
            x = self._project(data, b, x, jnp.ones_like(x, bool))
        else:
            for c in range(self.num_colors):
                x = self._project(data, b, x, data["colors"] == c)
        out = dict(st)
        out["x"] = x
        return out
