"""Equation scalers applied around a solve.

Analogs of src/scalers/ (binormalization.cu:1 518 LoC,
nbinormalization.cu:1 647 LoC, diagonal_symmetric.cu:1 267 LoC; factory
registration src/core.cu:687-689). A scaler turns A x = b into
(L A R) x' = L b with x = R x', where L/R are diagonal:

- DIAGONAL_SYMMETRIC: L = R = diag(|a_ii|)^{-1/2} (unit diagonal after
  scaling);
- BINORMALIZATION: symmetric binormalization (O. Livne, G. Golub,
  "Scaling by Binormalization", Numer. Algorithms 35, 2004 — public):
  fixed point on B = A .* A equalizing the scaled row 2-norms, like the
  reference's setup path (binormalization.cu:326);
- NBINORMALIZATION: the nonsymmetric norm variant: alternate row /
  column 2-norm equilibration (independent L and R), matching the
  reference's beta/gamma matvec formulation (nbinormalization.cu:411+).

Integration (Solver::setup/solve, src/solvers/solver.cu:465-476,
:668-673, :856-861): the solver tree is set up on the scaled matrix;
b is left-scaled in, x is right-scaled out; monitored residuals are in
the scaled system (same caveat as the reference, solver.cu:449).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import registry
from .errors import BadParametersError
from .matrix import CsrMatrix


def _seg_sum(v, seg, n):
    return jax.ops.segment_sum(v, seg, num_segments=n,
                               indices_are_sorted=True)


class Scaler:
    """Base: setup(A) computes diagonal left/right scale vectors."""

    def __init__(self, cfg, scope: str = "default"):
        self.cfg = cfg
        self.scope = scope
        self.left = None        # (n,)
        self.right = None       # (m,)

    def setup(self, A: CsrMatrix):
        raise NotImplementedError

    # -- application ------------------------------------------------------
    def scale_matrix(self, A: CsrMatrix) -> CsrMatrix:
        """Return L A R (values-only change; structure shared)."""
        if A.is_block:
            raise BadParametersError(
                f"{type(self).__name__}: scalar matrices only")
        rows, cols, vals = A.coo()
        new_vals = vals * self.left[rows] * self.right[cols]
        diag = None
        if A.has_external_diag:
            n = A.num_rows
            diag = A.diag * self.left * self.right[:n]
        return A.with_values(new_vals, diag)

    def scale_rhs(self, b):
        return b * self.left

    def to_scaled_x(self, x):
        return x / self.right

    def from_scaled_x(self, x):
        return x * self.right


@registry.scalers.register("DIAGONAL_SYMMETRIC")
class DiagonalSymmetricScaler(Scaler):
    """L = R = |diag(A)|^{-1/2} (diagonal_symmetric.cu)."""

    def setup(self, A: CsrMatrix):
        d = jnp.abs(A.diagonal())
        s = jnp.where(d > 0, 1.0 / jnp.sqrt(jnp.where(d > 0, d, 1.0)), 1.0)
        self.left = self.right = s
        return self


@registry.scalers.register("BINORMALIZATION")
class BinormalizationScaler(Scaler):
    """Symmetric binormalization on B = A.*A: fixed point
    x_i <- sqrt(x_i * avg / (B x)_i) driving x_i (Bx)_i to a constant;
    scale vectors are sqrt(x)."""

    ITERS = 30

    def setup(self, A: CsrMatrix):
        from .ops.spgemm import _fold_diag
        rows, cols, vals = _fold_diag(A).coo()
        n = A.num_rows
        B = vals * vals
        x = jnp.ones((n,), vals.dtype)
        for _ in range(self.ITERS):
            beta = _seg_sum(B * x[cols], rows, n)        # B x
            avg = jnp.mean(beta * x)
            safe = jnp.where(beta > 0, beta, 1.0)
            x = jnp.where(beta > 0, jnp.sqrt(x * avg / safe), x)
        s = jnp.sqrt(jnp.where(x > 0, x, 1.0))
        self.left = self.right = jnp.where(x > 0, s, 1.0)
        return self


@registry.scalers.register("NBINORMALIZATION")
class NBinormalizationScaler(Scaler):
    """Nonsymmetric norm binormalization: alternate row/column 2-norm
    equilibration (nbinormalization.cu beta/gamma iteration)."""

    ITERS = 50

    def setup(self, A: CsrMatrix):
        from .ops.spgemm import _fold_diag
        rows, cols, vals = _fold_diag(A).coo()
        n, m = A.num_rows, A.num_cols
        B = vals * vals
        x = jnp.ones((n,), vals.dtype)      # left^2
        y = jnp.ones((m,), vals.dtype)      # right^2
        for _ in range(self.ITERS):
            beta = _seg_sum(B * y[cols], rows, n)        # scaled row norms^2
            x = jnp.where(beta > 0, 1.0 / beta, 1.0)
            gamma = jnp.zeros((m,), vals.dtype).at[cols].add(B * x[rows])
            y = jnp.where(gamma > 0, 1.0 / gamma, 1.0)
        # balance so neither side carries all the magnitude
        scale = _seg_sum(B * y[cols], rows, n) * x
        mean = jnp.mean(jnp.where(scale > 0, scale, 1.0))
        self.left = jnp.sqrt(x) / jnp.sqrt(jnp.sqrt(mean))
        self.right = jnp.sqrt(y) / jnp.sqrt(jnp.sqrt(mean))
        return self


def make_scaler(name: str, cfg, scope: str = "default"):
    """ScalerFactory::allocate analog (src/core.cu:687-689)."""
    return registry.scalers.create(name, cfg, scope)
