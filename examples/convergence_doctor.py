"""Convergence doctor: diagnose a slow AMG configuration down to its
bottleneck level.

ROADMAP item 2's standing question — WHY is the classical path slow? —
used to be answered by staring at residual histories. The diagnostics
layer (telemetry/diagnostics.py, `diagnostics=1`) answers it
structurally: one in-trace probe cycle records the residual norm at
every level's cycle stages, and the report derives per-level reduction
factors, smoother effectiveness, a coarse-correction quality column and
a bottleneck-level attribution.

This example sets up a DELIBERATELY weak classical configuration (an
overdamped Jacobi smoother plus an aggressive strength threshold — a
classic mistuning) next to a healthy reference, solves the same 3D
Poisson system with both, and prints each hierarchy's diagnosis:

    python examples/convergence_doctor.py

Look for: the weak config's higher asymptotic convergence factor, the
per-level `level_reduction` column pointing at the bottleneck level,
and the `smoother_effectiveness` column showing WHERE the overdamped
smoother stops biting — that's the knob to fix first.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp

import amgx_tpu as amgx
from amgx_tpu.config import Config

amgx.initialize()

N = 24            # 24^3 = 13.8k rows: small enough to run anywhere

BASE = (
    "solver(s)=PCG, s:max_iters=120, s:tolerance=1e-8,"
    " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
    " s:store_res_history=1, s:preconditioner(amg)=AMG,"
    " amg:algorithm=CLASSICAL, amg:selector=PMIS,"
    " amg:interpolator=D1, amg:presweeps=1, amg:postsweeps=1,"
    " amg:max_iters=1, amg:coarse_solver=DENSE_LU_SOLVER,"
    " amg:min_coarse_rows=32, amg:max_levels=12, amg:diagnostics=1")

CONFIGS = {
    # healthy reference: L1-Jacobi with the stock strength threshold
    "healthy": BASE + ", amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
               " amg:strength_threshold=0.25",
    # mistuned: a badly overdamped plain Jacobi (relaxation_factor far
    # below useful) + a strength threshold that thins interpolation —
    # the cycle limps, and the doctor should say WHERE
    "mistuned": BASE + ", amg:smoother(sm)=BLOCK_JACOBI,"
                " sm:max_iters=1, sm:relaxation_factor=0.15,"
                " amg:strength_threshold=0.7",
}


def doctor(tag, cfg_str):
    A = amgx.gallery.poisson("7pt", N, N, N).init()
    b = jnp.ones(A.num_rows)
    slv = amgx.create_solver(Config.from_string(cfg_str))
    slv.setup(A)
    res = slv.solve(b)
    rep = res.report
    d = rep.diagnostics
    print(f"\n=== {tag} ===")
    print(f"status={res.status} iters={res.iterations} "
          f"solve={res.solve_time:.3f}s")
    h = rep.hierarchy
    print(f"hierarchy: {h['num_levels']} levels, "
          f"operator complexity {h['operator_complexity']:.2f}")
    acf = d["asymptotic_convergence_factor"]
    print(f"asymptotic convergence factor: "
          f"{'n/a' if acf is None else f'{acf:.3f}'} "
          f"(lower is better; >0.9 means the cycle barely bites)")
    print("  lvl     rows  level_red  presmooth  correction  "
          "postsmooth  smoother_eff")
    for row, hrow in zip(d["levels"], h["levels"]):
        def f(v):
            return "     n/a" if v is None else f"{v:8.3f}"
        print(f"  {row['level']:3d} {hrow['rows']:8d} "
              f"{f(row['level_reduction'])}   {f(row['presmooth_reduction'])}"
              f"   {f(row['correction_reduction'])}"
              f"    {f(row['postsmooth_reduction'])}"
              f"     {f(row['smoother_effectiveness'])}")
    bl = d["bottleneck_level"]
    print(f"bottleneck level: {bl} "
          f"(level_reduction {d['bottleneck_reduction']:.3f})")
    if bl is not None:
        # the shared diagnostics->deltas mapping (the serving
        # autotuner's candidate generator reads the same suggestions);
        # the doctor prints each distinct hint sentence once, in rule
        # order — the historical output, now derived from one source
        from amgx_tpu.telemetry.diagnostics import suggest_config_deltas
        hints = []
        for s in suggest_config_deltas(d):
            if s["hint"] and s["hint"] not in hints:
                hints.append(s["hint"])
        if hints:
            print("doctor says: " + "; ".join(hints))
    return res


def classical_fusion_before_after():
    """Before/after the classical-path fusion (ISSUE 12): the same
    classical config traced with `cycle_fusion=0` (the pre-fusion
    composition) and with the fused classical kernels, with the
    per-cycle kernel census from each trace. The diagnostics probe
    runs in both and must attribute the SAME bottleneck level — the
    fusion is a wall-clock change (HBM passes per cycle), not a
    numerical one — so the census is where the change shows: the
    smoothed DIA fine level collapses to exactly two fused kernels
    and its standalone SpMV/transfer passes disappear."""
    import re

    import jax

    from amgx_tpu.ops import pallas_spmv as ps

    cfg = (BASE + ", amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
           " amg:strength_threshold=0.25, amg:interp_max_elements=4,"
           " amg:max_levels=2, amg:min_coarse_rows=16")
    A = amgx.gallery.poisson("7pt", N, N, N,
                             dtype=jnp.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    print("\n=== classical-path fusion: before / after ===")
    for tag, extra in (("before (cycle_fusion=0)",
                        ", amg:cycle_fusion=0"),
                       ("after  (fused classical)", "")):
        with ps.force_pallas_interpret():
            slv = amgx.create_solver(Config.from_string(cfg + extra))
            slv.setup(A)
            res = slv.solve(b)
            pc = slv.preconditioner
            d = pc.solve_data()
            jaxpr = str(jax.make_jaxpr(
                lambda bb, xx: pc.amg.cycle(d["amg"], bb, xx))(
                    b, jnp.zeros_like(b)))
        census = {}
        for nm in re.findall(r'name="?([A-Za-z_0-9]+)"?', jaxpr):
            if nm.startswith(("_dia_", "_swell_")):
                census[nm] = census.get(nm, 0) + 1
        bl = res.report.diagnostics["bottleneck_level"]
        print(f"{tag}: iters={res.iterations} bottleneck_level={bl}"
              f" kernels/cycle={census or '{}'}")
    print("the fused trace runs the smoothed classical level as TWO "
          "kernels\n(_dia_smooth_restrict_call + "
          "_dia_prolong_smooth_call) with the standalone\nsmoother/"
          "SpMV/transfer passes gone; the bottleneck attribution is "
          "unchanged\n— fusion cuts HBM passes, not iterations.")


if __name__ == "__main__":
    healthy = doctor("healthy", CONFIGS["healthy"])
    mistuned = doctor("mistuned", CONFIGS["mistuned"])
    print(f"\nhealthy converged in {healthy.iterations} iters, "
          f"mistuned took {mistuned.iterations} "
          f"({mistuned.status}) — the table above says why.")
    classical_fusion_before_after()
