"""Serving fault-tolerance chaos demo: kill it, break it, watch it heal.

Walks the recovery substrate end to end with deterministic scripted
faults (resilience/faultinject.py service kinds):

1. CRASH + RECOVER — a journaled service (write-ahead requests +
   per-cycle solve checkpoints + persisted hierarchy structures + AOT
   executables) is killed mid-solve; its successor replays the
   journal, rebuilds the bucket WITHOUT a full AMG setup or a single
   retrace, and resumes the interrupted solve bit-identically.
2. BUILDER CRASH — a scripted exception inside the bucket build is
   retried behind an exponential backoff (serving_fault_policy
   BUILD_FAILED>retry_backoff) and the tickets still converge.
3. WEDGED BUCKET — a bucket whose progress heartbeat flatlines is
   quarantined by the supervisor and its work requeued.
4. OVERLOAD SHED — a burst beyond what the deadline allows is shed
   early with OVERLOADED (never a queued-then-missed surprise).
5. POSTMORTEM — the crash-surviving flight recorder's event trail
   (chaos injections, quarantines, shed decisions with their
   feasibility estimates, resetup routing) read back the way
   `tools/flightrec.py` would read a dead process's log, correlated
   with the journal by request trace id.

Run:  python examples/chaos_demo.py
"""
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import amgx_tpu as amgx  # noqa: E402
from amgx_tpu import gallery  # noqa: E402
from amgx_tpu.config import Config  # noqa: E402
from amgx_tpu.presets import SERVING_CG  # noqa: E402
from amgx_tpu.resilience import faultinject  # noqa: E402
from amgx_tpu.serving import SolveService  # noqa: E402
from amgx_tpu.telemetry import metrics  # noqa: E402


def main():
    amgx.initialize()
    root = tempfile.mkdtemp(prefix="amgx_chaos_demo_")
    durable = (f"serving_journal_dir={root}/journal,"
               f" serving_hierarchy_dir={root}/hier,"
               f" serving_aot_dir={root}/aot,"
               " serving_checkpoint_cycles=1")
    base_cfg = (SERVING_CG + ", serving_bucket_slots=4,"
                " serving_chunk_iters=1, s:tolerance=1e-12")
    A = gallery.poisson("7pt", 12, 12, 12).init()
    rng = np.random.default_rng(0)
    b = rng.standard_normal(A.num_rows)

    # -- 1. crash + recover ---------------------------------------------
    print("== 1. kill a journaled service mid-solve, recover ==")
    ref = SolveService(Config.from_string(base_cfg))
    rt = ref.submit(A, b)
    ref.drain()
    print(f"   uninterrupted reference: {rt.result.iterations} iters")

    victim = SolveService(Config.from_string(base_cfg + ", " + durable))
    vt = victim.submit(A, b, tenant="acme", request_key="demo-1")
    for _ in range(4):
        victim.step()                    # a few cycles...
    print(f"   victim killed mid-flight (done={vt.done})")
    del victim                           # ...then the process "dies"

    successor = SolveService(Config.from_string(base_cfg + ", " + durable))
    done = successor.drain()
    t = done[0]
    recovered_trace = t.trace_id
    same = np.array_equal(np.asarray(t.result.x), np.asarray(rt.result.x))
    print(f"   successor replayed the journal: {t.result.iterations} "
          f"iters, bit-identical={same}")
    print(f"   trace id survived the crash: {t.trace_id == vt.trace_id} "
          f"(both incarnations' spans share one Perfetto flow chain)")
    snap = metrics.snapshot()
    for k in ("serving.recovery.replayed", "serving.recovery.resumed",
              "serving.recovery.checkpoints", "amg.setup.restored",
              "serving.aot.load"):
        print(f"   {k:36s} {snap[k]}")
    retried = successor.submit(A, b, request_key="demo-1")
    print(f"   retried submit deduped against the journal: "
          f"done={retried.done} (no second solve)")

    # -- 2. builder crash + bounded retry -------------------------------
    print("== 2. builder crash -> retry_backoff ==")
    svc = SolveService(Config.from_string(
        base_cfg + ", serving_fault_policy=BUILD_FAILED>retry_backoff,"
                   " serving_retry_backoff_s=0.02"))
    with faultinject.inject("build_crash", fires=1):
        t = svc.submit(A, b)
        svc.drain()
    print(f"   build crashed once, retried, status={t.result.status}")

    # -- 3. wedged bucket -> supervisor quarantine -----------------------
    print("== 3. wedged bucket -> quarantine + requeue ==")
    svc = SolveService(Config.from_string(
        base_cfg + ", serving_supervisor_cycles=2"))
    t = svc.submit(A, b)
    svc.step()
    with faultinject.inject("step_wedge", fires=4):
        for _ in range(5):
            svc.step()                   # heartbeat flatlines...
    svc.drain()                          # ...rebuilt bucket finishes
    print(f"   quarantined={metrics.get('serving.recovery.quarantined')}"
          f" status={t.result.status}")

    # -- 4. overload shedding -------------------------------------------
    print("== 4. deadline-aware load shedding ==")
    svc = SolveService(Config.from_string(
        base_cfg + ", serving_shed_policy=deadline"))
    warm = svc.submit(A, b)
    svc.drain()                          # train the estimator
    burst = [svc.submit(A, rng.standard_normal(A.num_rows),
                        deadline_s=0.02) for _ in range(8)]
    svc.drain()
    shed = sum(t.result.status == "overloaded" for t in burst)
    missed = sum(t.result.status == "deadline_exceeded" for t in burst)
    print(f"   burst of 8 at a 20ms deadline: shed={shed} "
          f"(OVERLOADED, immediate), admitted-but-missed={missed}")

    # -- 5. postmortem: the flight recorder's event trail ----------------
    print("== 5. postmortem: flight-recorder readout ==")
    from amgx_tpu.telemetry import flightrec
    for e in flightrec.events(last=12):
        print("   " + flightrec.format_event(e))
    # the journal correlation tools/flightrec.py runs on a DEAD
    # process's directories: the trace id persisted at submit is the
    # join key between the event trail and the journaled request
    print(f"   (crash-recovered request's trace id: {recovered_trace}; "
          f"run `python tools/flightrec.py <flightrec_dir> "
          f"--journal {root}/journal` against a crashed service's "
          f"directories for the full correlated view)")
    print("done.")


if __name__ == "__main__":
    main()
