"""Resilient solve demo: a transient NaN fault, caught and retried.

Shows the resilience subsystem end to end (README "Solve status &
fallbacks"):

1. a CG solve is hit by an injected transient SpMV fault (one NaN at
   iteration 3 — the cosmic-ray model) and the in-trace health guards
   end it with status NAN_DETECTED instead of burning max_iters on a
   NaN storm;
2. the configured `fallback_policy=NAN_DETECTED>retry` chain re-solves:
   the fault spec has expired, the epoch-keyed jit cache recompiles
   clean, and the retry converges — the AMG-free CG tree, its setup,
   and the matrix are all reused;
3. a CG breakdown on an indefinite matrix (p.Ap <= 0) falls back to
   GMRES via `BREAKDOWN>switch_solver=GMRES`.

Runs on CPU (`JAX_PLATFORMS=cpu python examples/resilient_solve.py`)
or any accelerator. Instead of the programmatic `inject(...)` below,
the same fault can be armed from the environment:

    AMGX_TPU_FAULT_INJECT="spmv_nan:iteration=3:fires=1"
"""
import os
import sys

import numpy as np
import scipy.sparse as sp

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir))

import amgx_tpu as amgx  # noqa: E402
from amgx_tpu.config import Config  # noqa: E402
from amgx_tpu.resilience import SolveStatus, faultinject  # noqa: E402


def banner(title):
    print(f"\n=== {title} " + "=" * max(0, 60 - len(title)))


def main():
    amgx.initialize()

    # --- 1+2: transient NaN -> retry ---------------------------------
    banner("transient SpMV NaN -> NAN_DETECTED -> retry")
    A = amgx.gallery.poisson("5pt", 32, 32).init()
    b = np.ones(A.num_rows)
    cfg = Config.from_string(
        "solver=CG, max_iters=300, monitor_residual=1, tolerance=1e-8,"
        " convergence=RELATIVE_INI,"
        " fallback_policy=NAN_DETECTED>retry, max_fallback_attempts=2")
    slv = amgx.create_solver(cfg)       # -> ResilientSolver wrapper
    slv.setup(A)
    with faultinject.inject("spmv_nan", iteration=3, fires=1):
        res = slv.solve(b)
    print(f"final status : {res.status} ({res.iterations} iters)")
    print(f"chain        : {res.fallback_history}")
    assert res.status_code == SolveStatus.CONVERGED

    # --- 3: CG breakdown on an indefinite matrix -> GMRES ------------
    banner("indefinite matrix -> CG BREAKDOWN -> switch to GMRES")
    n = 64
    d = np.ones(n)
    d[::2] = -1.0
    off = 0.1 * np.ones(n - 1)
    Aind_sp = sp.diags([d, off, off], [0, 1, -1]).tocsr()
    Aind = amgx.CsrMatrix.from_scipy_like(
        Aind_sp.indptr, Aind_sp.indices, Aind_sp.data, n, n).init()
    cfg2 = Config.from_string(
        "solver=CG, max_iters=80, monitor_residual=1, tolerance=1e-8,"
        " convergence=RELATIVE_INI, gmres_n_restart=40,"
        " fallback_policy=BREAKDOWN>switch_solver=GMRES,"
        " max_fallback_attempts=1")
    slv2 = amgx.create_solver(cfg2)
    slv2.setup(Aind)
    res2 = slv2.solve(np.ones(n))
    print(f"final status : {res2.status} ({res2.iterations} iters)")
    print(f"chain        : {res2.fallback_history}")
    print(f"adopted tree : {slv2.solver.name}")
    assert res2.status_code == SolveStatus.CONVERGED

    print("\nresilient solves: OK")


if __name__ == "__main__":
    main()
