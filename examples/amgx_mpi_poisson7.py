#!/usr/bin/env python
"""Multi-device Poisson solve through the distributed C API — the
analog of the reference's MPI integration-test example
(examples/amgx_mpi_poisson7.c:274): generate a 7-pt Poisson system,
upload it as PER-RANK PIECES with global column ids (no global matrix
is assembled; the arranger builds the halo maps), and solve it SPMD
over the device mesh.

Where the reference runs `mpirun -n R` with one GPU per process, the
TPU-native framework is single-controller SPMD: the "ranks" are mesh
devices, and each AMGX_matrix_upload_distributed call contributes one
rank's piece, exactly as each MPI rank's call would.

    # 8 virtual CPU devices (no TPU needed):
    python examples/amgx_mpi_poisson7.py -n 8 --nx 8 --ny 8 --nz 64

    # on the real accelerator(s):
    python examples/amgx_mpi_poisson7.py --mode dDDI -c configs/FGMRES_AGGREGATION.json
"""
import argparse
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--ranks", type=int, default=0,
                    help="mesh size; 0 = all visible devices. >1 on a "
                         "CPU host forces that many virtual devices")
    ap.add_argument("--nx", type=int, default=8)
    ap.add_argument("--ny", type=int, default=8)
    ap.add_argument("--nz", type=int, default=64)
    ap.add_argument("-c", "--config", default=None)
    ap.add_argument("--mode", default="dDDI")
    args = ap.parse_args()

    if args.ranks > 1:
        # force virtual CPU devices BEFORE any jax import-time work
        from _cpu_backend import force_cpu
        force_cpu(args.ranks)
    import jax
    import numpy as np
    from amgx_tpu import capi

    R = args.ranks or len(jax.devices())

    def safe(rc, *out):
        assert rc == capi.RC.OK, capi.AMGX_get_error_string(rc)
        return out[0] if len(out) == 1 else (out if out else None)

    capi.AMGX_initialize()
    if args.config:
        cfg = safe(*capi.AMGX_config_create_from_file(args.config))
    else:
        cfg = safe(*capi.AMGX_config_create(
            "config_version=2, solver(s)=FGMRES, s:max_iters=100,"
            " s:tolerance=1e-8, s:convergence=RELATIVE_INI,"
            " s:gmres_n_restart=20, s:monitor_residual=1,"
            " s:print_solve_stats=1, s:preconditioner(amg)=AMG,"
            " amg:algorithm=AGGREGATION, amg:selector=SIZE_2,"
            " amg:smoother=JACOBI_L1, amg:max_iters=1,"
            " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=16"))
    rsc = safe(*capi.AMGX_resources_create_simple(cfg))
    mtx = safe(*capi.AMGX_matrix_create(rsc, args.mode))
    rhs = safe(*capi.AMGX_vector_create(rsc, args.mode))
    sol = safe(*capi.AMGX_vector_create(rsc, args.mode))

    # global 7-pt Poisson, z-slab partition: rank r owns a contiguous
    # block of grid planes — the example's px*py*pz=R decomposition
    # specialised to pz=R (the slab case the ring exchange rides)
    from amgx_tpu import gallery
    A = gallery.poisson("7pt", args.nx, args.ny, args.nz).init()
    n = A.num_rows
    n_local = -(-n // R)
    offsets = np.minimum(np.arange(R + 1) * n_local, n)

    dist = safe(*capi.AMGX_distribution_create(cfg))
    safe(capi.AMGX_distribution_set_partition_data(
        dist, capi.AMGX_DIST_PARTITION_OFFSETS, offsets))
    ro = np.asarray(A.row_offsets)
    ci = np.asarray(A.col_indices)
    va = np.asarray(A.values)
    for r in range(R):          # one call per "rank", as in MPI
        lo, hi = int(offsets[r]), int(offsets[r + 1])
        s, e = int(ro[lo]), int(ro[hi])
        safe(capi.AMGX_matrix_upload_distributed(
            mtx, n, hi - lo, e - s, 1, 1, ro[lo:hi + 1] - ro[lo],
            ci[s:e], va[s:e], None, dist))

    slv = safe(*capi.AMGX_solver_create(rsc, args.mode, cfg))
    safe(capi.AMGX_solver_setup(slv, mtx))
    safe(capi.AMGX_vector_bind(rhs, mtx))
    for r in range(R):
        lo, hi = int(offsets[r]), int(offsets[r + 1])
        safe(capi.AMGX_vector_upload_distributed(
            rhs, hi - lo, 1, np.ones(hi - lo)))
    safe(capi.AMGX_solver_solve_with_0_initial_guess(slv, rhs, sol))
    rc, its = capi.AMGX_solver_get_iterations_number(slv)
    x = safe(*capi.AMGX_vector_download(sol))
    import jax.numpy as jnp
    import amgx_tpu as amgx
    b = np.ones(n)
    res = np.linalg.norm(b - np.asarray(amgx.ops.spmv(A, jnp.asarray(x))))
    print(f"ranks={R} n={n}: {its} iterations, "
          f"true |r| = {res:.3e} (|b| = {np.linalg.norm(b):.3e})")
    assert res < 1e-6 * np.linalg.norm(b)


if __name__ == "__main__":
    main()
