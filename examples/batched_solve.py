"""Batched solves: many systems, one jitted program.

Demonstrates the three entry points of the batch subsystem
(amgx_tpu/batch/):

1. multi-RHS      — many right-hand sides against one matrix;
2. multi-matrix   — many same-pattern matrices (perturbed coefficients),
                    hierarchy structure built once, values spliced per
                    system;
3. RequestBatcher — a serving-style queue that buckets a mixed request
                    stream by sparsity-pattern fingerprint and pads each
                    bucket to a bounded ladder of batch sizes.

Run: python examples/batched_solve.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir))

import amgx_tpu as amgx  # noqa: E402
from amgx_tpu.batch import BatchedSolver, RequestBatcher  # noqa: E402
from amgx_tpu.config import Config  # noqa: E402
from amgx_tpu.presets import BATCHED_CG  # noqa: E402


def main():
    amgx.initialize()
    rng = np.random.default_rng(0)
    cfg = Config.from_string(BATCHED_CG)

    # -- 1. multi-RHS: 8 load cases against one stiffness matrix --------
    A = amgx.gallery.poisson("7pt", 16, 16, 16).init()
    solver = BatchedSolver(cfg)
    solver.setup(A)
    B = rng.standard_normal((8, A.num_rows))
    res = solver.solve_many(B)
    print(f"multi-RHS:    {res.batch_size} systems, "
          f"iters={res.iterations.tolist()}, "
          f"all converged={res.all_converged}, "
          f"{solver.trace_count} trace(s)")

    # -- 2. multi-matrix: same pattern, per-system coefficients ---------
    # (e.g. one mesh, 8 users' material parameters). The hierarchy
    # structure is reused; only Galerkin values differ per system.
    dix = np.asarray(A.diag_idx)
    mats = []
    for i in range(8):
        vals = np.asarray(A.values).copy()
        vals[dix] += 0.5 * i          # SPD shift, pattern unchanged
        mats.append(A.with_values(vals))
    res = solver.solve_many(B, matrices=mats)
    print(f"multi-matrix: iters={res.iterations.tolist()} "
          f"(better-conditioned systems freeze earlier), "
          f"{solver.trace_count} trace(s) total")

    # -- 3. request batcher: a mixed stream, bucketed + padded ----------
    A2 = amgx.gallery.poisson("5pt", 32, 32).init()
    batcher = RequestBatcher(cfg)
    tickets = [batcher.submit(M, rng.standard_normal(M.num_rows))
               for M in (mats[0], mats[1], mats[2], A2, A2)]
    batcher.drain()
    print("batcher dispatches (bucket, requests, padded-to):")
    for key, real, padded in batcher.dispatch_log:
        print(f"  {key[:12]}...  {real} -> {padded}")
    for t in tickets:
        assert t.result.converged
    print("all tickets solved")


if __name__ == "__main__":
    main()
