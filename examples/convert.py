#!/usr/bin/env python
"""Matrix/system format converter.

Analog of the reference CLI utility (/root/reference/examples/convert.c):
read a system in one supported format (MatrixMarket `.mtx` or the
binary system format) and write it in another, chosen by the output
extension (`.mtx` -> MatrixMarket, anything else -> binary).

Usage:
    python examples/convert.py input.mtx output.bin
    python examples/convert.py input.bin output.mtx
"""
import argparse
import sys

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__)),
    ".."))

import os  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    import jax  # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("input", help="input system (.mtx or binary)")
    ap.add_argument("output",
                    help="output path (.mtx -> MatrixMarket, else "
                         "binary)")
    args = ap.parse_args()

    from amgx_tpu.io import read_system, write_system
    A, b, x = read_system(args.input)
    fmt = ("matrixmarket" if args.output.lower().endswith(".mtx")
           else "binary")
    write_system(args.output, A, b, x, fmt=fmt)
    n = A.num_rows
    print(f"converted {args.input} -> {args.output} "
          f"({fmt}; {n} rows, {A.nnz} nnz"
          f"{', rhs' if b is not None else ''}"
          f"{', sol' if x is not None else ''})")


if __name__ == "__main__":
    main()
