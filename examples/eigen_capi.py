#!/usr/bin/env python
"""Eigensolver CLI through the C-API shim (power method / PageRank).

Analog of the reference's eigen_examples/ (eigensolver.c): read or
generate a matrix, create an eigensolver from config, solve, print the
eigenvalues.

Usage:
    python examples/eigen_capi.py -m <matrix.mtx> \
        [-c "eig_solver=LANCZOS, eig_wanted_count=3"] [-mode dDDI]
    python examples/eigen_capi.py --poisson 32 32 1 [-c ...]
"""
import argparse
import sys

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__)),
    ".."))

import os  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    # the axon TPU plugin ignores the env var; apply it via the
    # config API before any jax operation
    import jax  # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
from amgx_tpu import capi  # noqa: E402
from amgx_tpu.errors import RC  # noqa: E402


def safe(rc, *rest):
    if rc != RC.OK:
        print(f"AMGX error: {capi.AMGX_get_error_string(rc)}",
              file=sys.stderr)
        sys.exit(1)
    return rest[0] if len(rest) == 1 else rest


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-m", "--matrix", help="MatrixMarket system file")
    ap.add_argument("--poisson", nargs=3, type=int, metavar=("NX", "NY", "NZ"),
                    help="generate a Poisson matrix instead of reading one")
    ap.add_argument("-c", "--config",
                    default="eig_solver=POWER_ITERATION, eig_max_iters=1000,"
                            " eig_tolerance=1e-8, eig_eigenvector=1")
    ap.add_argument("-mode", default="dDDI")
    args = ap.parse_args()
    if not args.matrix and not args.poisson:
        ap.error("need -m or --poisson")

    safe(capi.AMGX_initialize())
    cfg = safe(*capi.AMGX_config_create(args.config))
    rsrc = safe(*capi.AMGX_resources_create_simple(cfg))
    A = safe(*capi.AMGX_matrix_create(rsrc, args.mode))
    x = safe(*capi.AMGX_vector_create(rsrc, args.mode))

    if args.matrix:
        safe(capi.AMGX_read_system(A, None, None, args.matrix))
    else:
        nx, ny, nz = args.poisson
        safe(capi.AMGX_generate_distributed_poisson_7pt(
            A, None, None, 1, 1, nx, ny, nz))

    es = safe(*capi.AMGX_eigensolver_create(rsrc, args.mode, cfg))
    safe(capi.AMGX_eigensolver_setup(es, A))
    safe(capi.AMGX_eigensolver_solve(es, x))
    eigs = safe(*capi.AMGX_eigensolver_get_eigenvalues(es))
    print("eigenvalues:", ", ".join(f"{v:.10g}" for v in eigs))

    for h, destroy in ((es, capi.AMGX_eigensolver_destroy),
                       (x, capi.AMGX_vector_destroy),
                       (A, capi.AMGX_matrix_destroy),
                       (rsrc, capi.AMGX_resources_destroy),
                       (cfg, capi.AMGX_config_destroy)):
        safe(destroy(h))
    safe(capi.AMGX_finalize())


if __name__ == "__main__":
    main()
