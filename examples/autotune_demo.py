"""Online autotuner: a mistuned hot fingerprint fixed live, on idle
capacity, with zero production traffic spent on the search.

The serving layer (serving/service.py) answers "solve this, again and
again" — but the CONFIG it serves with is whatever the operator wrote.
The autotuner (serving/autotune.py) closes the loop the convergence
doctor opened: when a fingerprint turns hot, it runs one diagnostics
probe, derives candidate config deltas from the same shared mapping
the doctor prints (telemetry/diagnostics.py `suggest_config_deltas`),
SHADOW-solves each candidate on idle scheduler cycles, and promotes a
winner only on a measured iterations-AND-wall improvement. The
promoted overlay persists in the hierarchy store, so a restarted
replica serves the tuned config from its first request.

This demo serves a deliberately overdamped BLOCK_JACOBI smoother (the
convergence-doctor classic), lets the tuner watch it turn hot, then
prints the decision trail from the flight recorder and the before /
after iteration counts:

    python examples/autotune_demo.py

Look for: the `autotune.hot` -> shadow runs -> `autotune.promote`
flight-recorder chain, the promoted overlay (the doctor's relaxation
hint, validated by measurement), and the re-served requests converging
in a fraction of the iterations — with zero requests rejected or
delayed while the search ran.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import amgx_tpu as amgx
from amgx_tpu.config import Config
from amgx_tpu.presets import BATCHED_CG
from amgx_tpu.serving import SolveService
from amgx_tpu.telemetry import flightrec

amgx.initialize()

N = 16            # 16^3 = 4.1k rows: small enough to run anywhere

# the mistuning: BLOCK_JACOBI damped nearly to a standstill — every
# request converges, slowly, and every request pays for it
MISTUNED = (
    BATCHED_CG + ", amg:smoother(sm2)=BLOCK_JACOBI,"
    " sm2:max_iters=1, sm2:relaxation_factor=0.02,"
    " serving_bucket_slots=2, serving_chunk_iters=2")

root = tempfile.mkdtemp(prefix="amgx_autotune_demo_")
cfg = Config.from_string(
    MISTUNED + ", autotune=1, autotune_hot_requests=4,"
    " autotune_hot_exec_share=0.0,"
    f" serving_hierarchy_dir={root}/hier,"
    f" serving_journal_dir={root}/journal")

A = amgx.gallery.poisson("7pt", N, N, N).init()
rng = np.random.default_rng(7)
rhs = [rng.standard_normal(A.num_rows) for _ in range(8)]

svc = SolveService(cfg)

print(f"== serving {len(rhs)} requests with the mistuned config ==")
before = [svc.submit(A, b) for b in rhs]
svc.drain(timeout_s=600)
pre = sorted(t.result.iterations for t in before)
print(f"   iterations (median): {pre[len(pre) // 2]}"
      f"   all converged: {all(t.result.converged for t in before)}")

print("\n== idle cycles: the tuner probes, shadow-solves, decides ==")
for _ in range(24):
    svc.step()
    if svc.stats()["autotune"]["promoted"]:
        break

snap = svc.stats()["autotune"]
rec = next(iter(snap["fingerprints"].values()))
print(f"   phase: {rec['phase']}   knob: {rec['knob']}"
      f"   overlay: {rec['overlay']}")

print("\n== decision trail (flight recorder) ==")
for ev in flightrec.events():
    if str(ev.get("kind", "")).startswith("autotune."):
        keys = [k for k in ("knob", "deltas", "baseline_iters",
                            "tuned_iters", "speedup_x", "decision")
                if k in ev]
        detail = ", ".join(f"{k}={ev[k]}" for k in keys)
        print(f"   {ev['kind']:<22} {detail}")

print("\n== the same requests, re-served under the promoted overlay ==")
after = [svc.submit(A, b) for b in rhs]
svc.drain(timeout_s=600)
post = sorted(t.result.iterations for t in after)
print(f"   iterations (median): {post[len(post) // 2]}"
      f"   all converged: {all(t.result.converged for t in after)}")
print(f"\n   {pre[len(pre) // 2]} -> {post[len(post) // 2]} iterations"
      " — tuned on idle capacity, validated by shadow measurement,"
      " persisted for the next restart.")
