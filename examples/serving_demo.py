"""Mixed-tenant serving demo: the production solve service end to end.

Drives `amgx_tpu.serving.SolveService` with a synthetic multi-tenant
load — a hot tenant streaming same-pattern systems with perturbed
coefficients (hierarchy-cache + value-resetup steady state), a cold
tenant on a second mesh, and a latency-bound tenant whose tight
deadlines must complete with DEADLINE_EXCEEDED instead of stalling
anyone else. Prints per-tenant outcomes, latency percentiles, and the
serving counters that tell the routing story.

Run:  python examples/serving_demo.py
"""
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import amgx_tpu as amgx  # noqa: E402
from amgx_tpu import gallery  # noqa: E402
from amgx_tpu.config import Config  # noqa: E402
from amgx_tpu.presets import SERVING_CG  # noqa: E402
from amgx_tpu.serving import SolveService  # noqa: E402
from amgx_tpu.telemetry import metrics  # noqa: E402


def shifted(A, c):
    """Same-pattern coefficient perturbation (A + c*I)."""
    vals = np.asarray(A.values).copy()
    vals[np.asarray(A.diag_idx)] += c
    return A.with_values(vals)


def main():
    amgx.initialize()
    aot_dir = tempfile.mkdtemp(prefix="amgx_serving_demo_")
    cfg = Config.from_string(
        SERVING_CG + ", serving_bucket_slots=4, serving_chunk_iters=4,"
        f" serving_aot_dir={aot_dir}")
    svc = SolveService(cfg)
    svc.start()                            # background scheduler

    hot = gallery.poisson("7pt", 16, 16, 16).init()
    cold = gallery.poisson("7pt", 20, 20, 20).init()
    rng = np.random.default_rng(0)
    base = metrics.snapshot()

    tickets = []
    for i in range(12):                    # hot tenant: one mesh, many
        A_i = shifted(hot, 0.05 * (i % 4))  # coefficient updates
        tickets.append(svc.submit(A_i, rng.standard_normal(hot.num_rows),
                                  tenant="hot"))
    for i in range(3):                     # cold tenant: second mesh
        tickets.append(svc.submit(cold,
                                  rng.standard_normal(cold.num_rows),
                                  tenant="cold"))
    for i in range(3):                     # latency-bound tenant:
        A_i = shifted(hot, 0.31)           # impossible deadlines
        tickets.append(svc.submit(A_i, rng.standard_normal(hot.num_rows),
                                  tenant="slo", deadline_s=1e-6))

    for t in tickets:
        t.wait(timeout=600)
    svc.stop()

    cur = metrics.snapshot()
    lat = sorted(1e3 * t.latency_s for t in tickets if t.done)
    print("=== per-tenant outcomes ===")
    for name, tally in sorted(svc.stats()["tenants"].items()):
        print(f"  {name:5s} {tally}")
    print("=== tickets ===")
    for t in tickets[:3] + tickets[-3:]:
        print(f"  tenant={t.tenant:5s} status={t.result.status:18s}"
              f" iters={t.result.iterations:3d}"
              f" latency={1e3 * t.latency_s:8.1f} ms")
    print("=== latency ===")
    print(f"  p50 {lat[len(lat) // 2]:.1f} ms   "
          f"p99 {lat[min(len(lat) - 1, int(0.99 * len(lat)))]:.1f} ms")
    print("=== routing counters (delta) ===")
    for k in ("serving.cache.hit", "serving.cache.miss",
              "amg.setup.full", "amg.resetup.value",
              "serving.retrace", "serving.deadline_miss"):
        print(f"  {k:25s} {int(cur[k] - base.get(k, 0))}")
    print(f"(AOT store: {aot_dir} — restart this script with the same "
          f"serving_aot_dir and serving.retrace stays 0)")


if __name__ == "__main__":
    main()
