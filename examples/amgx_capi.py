#!/usr/bin/env python
"""Single-device MatrixMarket solve through the C-API shim.

Line-for-line analog of the reference CLI example
(/root/reference/examples/amgx_capi.c:162-318): parse -m/-c arguments,
initialize, register a print callback, create config/resources/matrix/
vectors/solver, read the system, setup, solve, report, destroy.

Usage (examples/matrix.mtx is the shipped 12-row demo system, the
analog of the reference's examples/matrix.mtx):
    python examples/amgx_capi.py -m examples/matrix.mtx \
        -c configs/FGMRES_AGGREGATION.json [-mode dDDI] [-it <max_iters>]
"""
import argparse
import sys

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__)),
    ".."))

import os  # noqa: E402
if os.environ.get("JAX_PLATFORMS"):
    # the axon TPU plugin ignores the env var; apply it via the
    # config API before any jax operation
    import jax  # noqa: E402
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
from amgx_tpu import capi  # noqa: E402
from amgx_tpu.errors import RC  # noqa: E402


def safe(rc, *rest):
    """AMGX_SAFE_CALL analog."""
    if rc != RC.OK:
        print(f"AMGX error: {capi.AMGX_get_error_string(rc)}",
              file=sys.stderr)
        sys.exit(1)
    return rest[0] if len(rest) == 1 else rest


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-m", "--matrix", required=True,
                    help="MatrixMarket (or %%AMGX binary) system file")
    ap.add_argument("-c", "--config", required=True,
                    help="solver config (JSON or flat string file)")
    ap.add_argument("-mode", default="dDDI", help="precision mode")
    ap.add_argument("-it", type=int, default=None, help="max iterations")
    args = ap.parse_args()

    safe(capi.AMGX_initialize())
    capi.AMGX_register_print_callback(
        lambda msg, length: sys.stdout.write(msg))

    rc, major, minor = capi.AMGX_get_api_version()
    print(f"amgx_tpu api version: {major}.{minor}")

    cfg = safe(*capi.AMGX_config_create_from_file(args.config))
    if args.it is not None:
        safe(capi.AMGX_config_add_parameters(
            cfg, f"config_version=2, default:max_iters={args.it}"))
    rsrc = safe(*capi.AMGX_resources_create_simple(cfg))
    A = safe(*capi.AMGX_matrix_create(rsrc, args.mode))
    b = safe(*capi.AMGX_vector_create(rsrc, args.mode))
    x = safe(*capi.AMGX_vector_create(rsrc, args.mode))
    solver = safe(*capi.AMGX_solver_create(rsrc, args.mode, cfg))

    safe(capi.AMGX_read_system(A, b, x, args.matrix))
    rc, n, bx, by = capi.AMGX_matrix_get_size(A)
    print(f"matrix: {n} rows, block {bx}x{by}")

    safe(capi.AMGX_solver_setup(solver, A))
    safe(capi.AMGX_solver_solve(solver, b, x))

    status = safe(*capi.AMGX_solver_get_status(solver))
    iters = safe(*capi.AMGX_solver_get_iterations_number(solver))
    print(f"status: {'success' if status == 0 else 'failed'}, "
          f"iterations: {iters}")

    for h, destroy in ((solver, capi.AMGX_solver_destroy),
                       (x, capi.AMGX_vector_destroy),
                       (b, capi.AMGX_vector_destroy),
                       (A, capi.AMGX_matrix_destroy),
                       (rsrc, capi.AMGX_resources_destroy),
                       (cfg, capi.AMGX_config_destroy)):
        safe(destroy(h))
    safe(capi.AMGX_finalize())
    sys.exit(0 if status == 0 else 1)


if __name__ == "__main__":
    main()
