"""Fleet serving demo: the fingerprint-affine replica router end to end.

Drives `amgx_tpu.serving.FleetRouter` — two SolveService replicas
behind one submit/step/drain surface — with a mixed load: a HOT tenant
streaming same-pattern systems (rendezvous affinity pins the pattern
to one replica, every repeat rides its warm value-resetup path), a
COLD tenant on a second mesh (least-loaded cold placement puts it on
the other replica), and a BURSTY tenant whose same-fingerprint burst
exercises queue buildup on its home replica. Prints per-request
replica attribution, the per-replica route counters (warm|cold|spill
— the affinity proof), and the merged fleet-wide metrics snapshot
with per-replica latency series kept apart by their `replica` label.

Run:  python examples/fleet_demo.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import amgx_tpu as amgx  # noqa: E402
from amgx_tpu import gallery  # noqa: E402
from amgx_tpu.config import Config  # noqa: E402
from amgx_tpu.presets import SERVING_CG  # noqa: E402
from amgx_tpu.serving import FleetRouter  # noqa: E402


def shifted(A, c):
    """Same-pattern coefficient perturbation (A + c*I)."""
    vals = np.asarray(A.values).copy()
    vals[np.asarray(A.diag_idx)] += c
    return A.with_values(vals)


def main():
    amgx.initialize()
    cfg = Config.from_string(
        SERVING_CG + ", serving_bucket_slots=4, serving_chunk_iters=4,"
        " serving_bucket_ladder=1|2|4")
    fleet = FleetRouter.build(cfg, n_replicas=2)

    hot = gallery.poisson("7pt", 16, 16, 16).init()
    cold = gallery.poisson("7pt", 20, 20, 20).init()
    rng = np.random.default_rng(0)

    tickets = []
    # hot tenant: one mesh, many coefficient updates — submitted one
    # at a time so the bucket-width ladder sees singleton queues
    for i in range(6):
        A_i = shifted(hot, 0.05 * (i % 4))
        tickets.append(fleet.submit(
            A_i, rng.standard_normal(hot.num_rows), tenant="hot"))
        fleet.step()
    # cold tenant: a second mesh — the router's least-loaded cold
    # placement lands it on the OTHER replica
    for i in range(3):
        tickets.append(fleet.submit(
            cold, rng.standard_normal(cold.num_rows), tenant="cold"))
    # bursty tenant: a same-fingerprint burst arriving at once — the
    # ladder picks a wider bucket rung for the burst's build
    for i in range(4):
        A_i = shifted(hot, 0.31)
        tickets.append(fleet.submit(
            A_i, rng.standard_normal(hot.num_rows), tenant="bursty"))
    fleet.drain(timeout_s=600)

    print("=== tickets (replica attribution) ===")
    for t in tickets:
        print(f"  tenant={t.tenant:6s} replica={t.replica:3s} "
              f"route={t.route:5s} status={t.result.status:10s} "
              f"latency={1e3 * t.latency_s:7.1f} ms")
    print("=== per-replica route counters ===")
    for rid, counts in sorted(fleet.stats()["routes"].items()):
        print(f"  {rid}: {counts}")
    print("=== per-replica service stats ===")
    for rid, st in sorted(fleet.stats()["replicas"].items()):
        print(f"  {rid}: live_buckets={st['live_buckets']} "
              f"bucket_ladder={st['bucket_ladder']} "
              f"tenants={sorted(st['tenants'])}")
    print("=== merged fleet snapshot (replica-labeled series) ===")
    merged = fleet.fleet_snapshot()
    for key in sorted(merged):
        if key.startswith("serving.solve_latency_s"):
            v = merged[key]
            p50 = v.get("p50")
            print(f"  {key:60s} count={v['count']:3d} "
                  f"p50={-1 if p50 is None else round(1e3 * p50, 1)} ms")


if __name__ == "__main__":
    main()
