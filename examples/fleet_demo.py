"""Fleet serving demo: the fingerprint-affine replica router end to end.

Drives `amgx_tpu.serving.FleetRouter` — two SolveService replicas
behind one submit/step/drain surface — with a mixed load: a HOT tenant
streaming same-pattern systems (rendezvous affinity pins the pattern
to one replica, every repeat rides its warm value-resetup path), a
COLD tenant on a second mesh (least-loaded cold placement puts it on
the other replica), and a BURSTY tenant whose same-fingerprint burst
exercises queue buildup on its home replica. Prints per-request
replica attribution, the per-replica route counters (warm|cold|spill
— the affinity proof), and the merged fleet-wide metrics snapshot
with per-replica latency series kept apart by their `replica` label.

Act two kills a replica mid-load: a scripted `replica_kill` chaos
fault crashes one scheduler while journaled requests are queued
against it. The health monitor declares it REPLICA_DEAD on the next
tick, failover re-submits its work to the survivor, the survivor
adopts the dead replica's journal (checkpointed solves resume under
their original trace ids), and the flight-recorder postmortem names
the whole incident — kill, failover, adoption, rehome — on one trail.

Run:  python examples/fleet_demo.py
"""
import os
import shutil
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import amgx_tpu as amgx  # noqa: E402
from amgx_tpu import gallery  # noqa: E402
from amgx_tpu.config import Config  # noqa: E402
from amgx_tpu.presets import SERVING_CG  # noqa: E402
from amgx_tpu.resilience import faultinject  # noqa: E402
from amgx_tpu.serving import FleetRouter  # noqa: E402
from amgx_tpu.telemetry import flightrec  # noqa: E402


def shifted(A, c):
    """Same-pattern coefficient perturbation (A + c*I)."""
    vals = np.asarray(A.values).copy()
    vals[np.asarray(A.diag_idx)] += c
    return A.with_values(vals)


def main():
    amgx.initialize()
    cfg = Config.from_string(
        SERVING_CG + ", serving_bucket_slots=4, serving_chunk_iters=4,"
        " serving_bucket_ladder=1|2|4")
    fleet = FleetRouter.build(cfg, n_replicas=2)

    hot = gallery.poisson("7pt", 16, 16, 16).init()
    cold = gallery.poisson("7pt", 20, 20, 20).init()
    rng = np.random.default_rng(0)

    tickets = []
    # hot tenant: one mesh, many coefficient updates — submitted one
    # at a time so the bucket-width ladder sees singleton queues
    for i in range(6):
        A_i = shifted(hot, 0.05 * (i % 4))
        tickets.append(fleet.submit(
            A_i, rng.standard_normal(hot.num_rows), tenant="hot"))
        fleet.step()
    # cold tenant: a second mesh — the router's least-loaded cold
    # placement lands it on the OTHER replica
    for i in range(3):
        tickets.append(fleet.submit(
            cold, rng.standard_normal(cold.num_rows), tenant="cold"))
    # bursty tenant: a same-fingerprint burst arriving at once — the
    # ladder picks a wider bucket rung for the burst's build
    for i in range(4):
        A_i = shifted(hot, 0.31)
        tickets.append(fleet.submit(
            A_i, rng.standard_normal(hot.num_rows), tenant="bursty"))
    fleet.drain(timeout_s=600)

    print("=== tickets (replica attribution) ===")
    for t in tickets:
        print(f"  tenant={t.tenant:6s} replica={t.replica:3s} "
              f"route={t.route:5s} status={t.result.status:10s} "
              f"latency={1e3 * t.latency_s:7.1f} ms")
    print("=== per-replica route counters ===")
    for rid, counts in sorted(fleet.stats()["routes"].items()):
        print(f"  {rid}: {counts}")
    print("=== per-replica service stats ===")
    for rid, st in sorted(fleet.stats()["replicas"].items()):
        print(f"  {rid}: live_buckets={st['live_buckets']} "
              f"bucket_ladder={st['bucket_ladder']} "
              f"tenants={sorted(st['tenants'])}")
    print("=== merged fleet snapshot (replica-labeled series) ===")
    merged = fleet.fleet_snapshot()
    for key in sorted(merged):
        if key.startswith("serving.solve_latency_s"):
            v = merged[key]
            p50 = v.get("p50")
            print(f"  {key:60s} count={v['count']:3d} "
                  f"p50={-1 if p50 is None else round(1e3 * p50, 1)} ms")

    failover_act(hot, rng)


def failover_act(hot, rng):
    """Act two: kill one of two replicas under journaled load, watch
    the survivor adopt its journal, and read the postmortem."""
    print()
    print("=== ACT TWO: replica kill + journal adoption ===")
    jdir = tempfile.mkdtemp(prefix="fleet_demo_journal_")
    try:
        cfg = Config.from_string(
            SERVING_CG + ", serving_bucket_slots=2,"
            " serving_chunk_iters=2, serving_checkpoint_cycles=1,"
            f" serving_journal_dir={jdir}")
        fleet = FleetRouter.build(cfg, n_replicas=2)
        tickets = [fleet.submit(shifted(hot, 0.05 * i),
                                rng.standard_normal(hot.num_rows),
                                tenant="hot")
                   for i in range(4)]
        victim = tickets[0].replica
        fleet.step()                       # let work start on the victim
        seq0 = flightrec.recorder().last_seq
        print(f"  killing {victim} mid-flight "
              f"({sum(t.replica == victim for t in tickets)} tickets "
              f"homed there) ...")
        with faultinject.inject("replica_kill", fires=1, target=victim):
            fleet.drain(timeout_s=600)
        lost = sum(not (t.done and t.result.converged) for t in tickets)
        print(f"  survivors finished everything: lost={lost}")
        for t in tickets:
            print(f"    trace={t.trace_id} replica={t.replica:3s} "
                  f"status={t.result.status}")
        hs = fleet.health_snapshot()
        print(f"  health[{victim}]: down={hs[victim]['down']} "
              f"state={hs[victim]['state']} "
              f"last_event={hs[victim]['last_event']}")
        print("  --- flight-recorder postmortem (the incident trail) ---")
        for e in flightrec.events(kind="fleet.", since_seq=seq0):
            print("   " + flightrec.format_event(e))
        for e in flightrec.events(kind="serving.resume", since_seq=seq0):
            print("   " + flightrec.format_event(e))
        # rolling restart: bring the replica back into rendezvous
        fleet.restore_replica(victim)
        print(f"  restored {victim}: "
              f"available={fleet.health_snapshot()[victim]['state']}")
    finally:
        shutil.rmtree(jdir, ignore_errors=True)


if __name__ == "__main__":
    main()
