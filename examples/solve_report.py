"""Telemetry walkthrough: SolveReport, counters, spans, Perfetto export.

Solves a 32^3 Poisson system with an AMG-preconditioned CG and shows
every surface of the telemetry subsystem:

- the structured `SolveReport` attached to the result (per-iteration
  residuals, final status, per-level kernel activity, wall times),
- schema validation against telemetry/report_schema.json,
- the machine-readable report sink through the print callback,
- the process-wide counter/gauge registry dump,
- the hierarchical span timers and their Perfetto trace export.

Run:  python examples/solve_report.py
Then open solve_report_trace.json in https://ui.perfetto.dev/ (or
chrome://tracing) for the host-side timeline.
"""
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

import amgx_tpu as amgx  # noqa: E402
from amgx_tpu import output, profiling  # noqa: E402
from amgx_tpu.config import Config  # noqa: E402
from amgx_tpu.telemetry import metrics, spans, validate_report  # noqa: E402

amgx.initialize()
metrics.reset()
profiling.reset_timers()

cfg = Config.from_string(
    "solver(s)=PCG, s:max_iters=100, s:tolerance=1e-8,"
    " s:convergence=RELATIVE_INI, s:monitor_residual=1,"
    " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
    " amg:selector=SIZE_2, amg:smoother(sm)=JACOBI_L1, sm:max_iters=1,"
    " amg:presweeps=1, amg:postsweeps=1, amg:max_iters=1,"
    " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=32,"
    " amg:max_levels=20, amg:structure_reuse_levels=-1")

A = amgx.gallery.poisson("7pt", 32, 32, 32).init()
b = np.ones(A.num_rows)

solver = amgx.create_solver(cfg)
solver.setup(A)
result = solver.solve(b)

# -- the structured report -------------------------------------------------
report = result.report
print(f"status={report.status}  iters={report.iterations}  "
      f"final_res={report.res_norm:.3e}  solve_s={report.solve_time_s:.3f}")
print("per-level activity:")
for row in report.levels:
    print("  ", row)
errors = validate_report(report.to_dict())
print("schema valid:", not errors)

# coefficient replace: the resetup routes through the value path and
# the routing counters record it
solver.resetup(A)

# -- machine-readable sink through the print callback ----------------------
captured = []
output.register_print_callback(lambda msg, _n: captured.append(msg))
report.emit(include_counters=True)
output.register_print_callback(None)
doc = json.loads("".join(captured))
print("emitted report keys:", sorted(doc["amgx_report"].keys()))

# -- counter registry ------------------------------------------------------
print("counters (nonzero):")
for name, value in sorted(metrics.snapshot().items()):
    if value:
        print(f"  {name} = {value}")

# -- span timers + Perfetto export -----------------------------------------
print()
print(profiling.format_timers())
n_events = spans.export_chrome_trace("solve_report_trace.json")
print(f"wrote solve_report_trace.json ({n_events} span events) — "
      "load it in https://ui.perfetto.dev/")
