#!/usr/bin/env python
"""Cross-round bench-regression sentinel.

Every round the driver records a `BENCH_r<NN>.json` (and
`MULTICHIP_r<NN>.json`) wrapper around `bench.py`'s output. Until now,
"did `northstar_256^3_setup_warm_s` recover?" was answered by a human
reading two JSON files; this tool answers it mechanically, every round:

1. LOAD every `BENCH_r*.json` / `MULTICHIP_r*.json` in the repo root.
   A wrapper's `parsed` payload is preferred; when the driver's bounded
   stdout capture lost the parse (round 5: `parsed: null`), scalar
   `"key": number` pairs are RECOVERED from the captured `tail` text —
   so a truncated round still contributes every metric its tail kept.
   Rounds key on the artifact's own `round` stamp (bench.py
   schema_version >= 2), falling back to the wrapper's `n` field and,
   last, digits in the filename.

2. EXTRACT the declared metric-series catalog (`SERIES` below: warm
   setups, resetup_first_over_steady, solve walls, fused speedups,
   observability overhead, accounted fractions, serving throughput...).
   The catalog is declared like the telemetry registry's counters —
   each series names its direction (lower/higher is better) and a
   relative regression tolerance sized to cross-round rig noise.

3. WRITE `BENCH_HISTORY.json` (machine-readable trend store) and
   `BENCH_HISTORY.md` (a round-by-round trend table per series).

4. EXIT NONZERO when any tracked series' LATEST value regressed beyond
   its declared tolerance against the BEST of all prior rounds, naming
   the offending metric(s) — the standing demo case is r05's
   `northstar_256^3_setup_warm_s` = 17.37 s vs r03's 5.87 s.

Modes:
    python tools/bench_history.py             # full run over the repo
    python tools/bench_history.py --root DIR  # run over DIR's artifacts
    python tools/bench_history.py --smoke     # artifact well-formedness
        self-check (tier-1-reachable): every BENCH_r*.json must load as
        JSON with the wrapper shape and the extraction machinery must
        produce rounds + series; regressions do NOT fail smoke mode
        (they are performance facts, not artifact malformations).
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(_HERE)

HISTORY_SCHEMA_VERSION = 1

# ---------------------------------------------------------------------------
# the declared metric-series catalog
# ---------------------------------------------------------------------------
# (name, direction, rel_tolerance, doc)
#   direction: "lower" = smaller is better (walls), "higher" = larger is
#   better (speedups, fractions, throughput)
#   rel_tolerance: latest may be worse than best-of-prior by this
#   relative margin before it flags — sized to the observed cross-round
#   rig noise (shared-CPU-host benches swing tens of percent; a real
#   regression like r05's 3x warm-setup blowup clears any of these)
SERIES: Tuple[Tuple[str, str, float, str], ...] = (
    ("flagship_128^3_setup_warm_s", "lower", 0.40,
     "flagship 128^3 warm hierarchy setup wall (s)"),
    ("flagship_128^3_solve_s", "lower", 0.35,
     "flagship 128^3 solve wall to 1e-8 (s)"),
    ("flagship_128^3_resetup_s", "lower", 0.50,
     "flagship 128^3 steady-state value-resetup wall (s)"),
    ("flagship_128^3_resetup_first_over_steady", "lower", 1.0,
     "first-resetup trace-reuse ratio (the eager-chain fix's guard)"),
    ("flagship_128^3_setup_accounted_fraction", "higher", 0.10,
     "disjoint amg.* span sum over the warm setup wall (>=0.9 contract)"),
    ("northstar_256^3_setup_warm_s", "lower", 0.40,
     "256^3 north-star warm setup wall (s) — the r05 regression's home"),
    ("northstar_256^3_solve_s", "lower", 0.35,
     "256^3 north-star solve wall (s)"),
    ("northstar_256^3_resetup_s", "lower", 0.50,
     "256^3 north-star steady-state value-resetup wall (s)"),
    ("classical_pmis_d2_128^3_setup_warm_s", "lower", 0.40,
     "classical PMIS+D2 128^3 warm setup wall (s) — ROADMAP item 2"),
    ("classical_pmis_d2_128^3_solve_s", "lower", 0.40,
     "classical PMIS+D2 128^3 solve wall (s)"),
    # ISSUE 12 classical-fusion headline walls: recorded from r06 on
    # (the fused classical path + device selector land between r05 and
    # r06), so the 24x classical-vs-flagship gap is sentinel-tracked
    ("classical_128^3_setup_s", "lower", 0.40,
     "classical 128^3 warm setup wall (s), fused-classical era — the "
     "24x-gap tentpole's setup target (< 10 s)"),
    ("classical_128^3_solve_s", "lower", 0.40,
     "classical 128^3 solve wall (s), fused-classical era — the "
     "24x-gap tentpole's solve target (< 2 s)"),
    # ISSUE 15 plan-split RAP: recorded from r06 on (the RapPlan
    # structure/value split lands between r05 and r06); the CPU-rig
    # measurement lives in BENCH_spgemm.json until then
    ("spgemm_plan_speedup", "higher", 0.25,
     "plan-split vs eager Galerkin RAP warm-setup speedup, paired "
     "replay on the flagship 128^3 (x)"),
    ("classical_128^3_rap_s", "lower", 0.40,
     "classical 128^3 summed per-level RAP span wall in the warm "
     "setup (s) — the plan-split tentpole's attribution target"),
    # ISSUE 14 mixed-precision headline: recorded from r06 on (the
    # bf16 fused path lands between r05 and r06). ROADMAP item 5's TPU
    # targets live here: flagship bf16 solve <= 0.18 s, northstar 256^3
    # solve <= 1.9 s at matched final residuals
    ("flagship_128^3_solve_bf16_s", "lower", 0.35,
     "flagship 128^3 solve wall at solve_precision=bfloat16 (s) — "
     "mixed-precision era; target <= 0.18 s on TPU"),
    ("mixed_precision_speedup", "higher", 0.25,
     "flagship solve wall ratio float/bfloat16, paired replay on one "
     "system at matched final residuals (x)"),
    ("spmv_vs_ceiling", "higher", 0.50,
     "DIA SpMV achieved bandwidth vs the rig's streaming ceiling "
     "(tunnel bandwidth swings ~2x run to run — r02-r04 recorded "
     "0.79/1.20/0.74 — so the tolerance is sized to that noise)"),
    ("fused_smooth_residual_speedup", "higher", 0.25,
     "fused smooth(2)+residual vs unfused compose (x)"),
    ("fused_cycle_speedup_64^3", "higher", 0.25,
     "fused vs unfused whole-cycle wall on one hierarchy (x)"),
    ("obs_overhead_pct", "lower_abs", 3.0,
     "telemetry-instrumented per-iteration overhead (abs pct gate, "
     "not relative-to-prior: the target is 0)"),
    ("serving_trace_overhead_pct", "lower_abs", 3.0,
     "request-path tracing (serving_tracing=1 vs 0) paired-median "
     "per-request overhead (abs pct gate; host dict appends only, "
     "the target is 0)"),
    ("serving_solves_per_s", "higher", 0.40,
     "serving sustained throughput under the open-loop bench load"),
    ("serving_p99_ms", "lower", 0.60,
     "serving p99 submit-to-complete latency (ms)"),
    # ISSUE 16 fleet serving: recorded from r06 on (the
    # fingerprint-affine FleetRouter lands between r05 and r06). The
    # scaling headline on the 1-core rig is the aggregate-cache-
    # capacity + affinity effect (see bench.py bench_fleet docstring),
    # so it can legitimately sit above 1.0
    ("fleet_scaling_efficiency", "higher", 0.40,
     "fleet 2-replica vs single-replica sustained-throughput scaling "
     "per replica (fleet_scaling_x / n_replicas) under the "
     "cache-capacity wave load"),
    ("fleet_p99_at_2x_ms", "lower", 0.60,
     "p99 latency of ADMITTED fleet requests at 2x the fleet's "
     "measured closed-loop service rate (ms) — must stay within the "
     "deadline budget, sheds classified OVERLOADED"),
    # ISSUE 17 fleet failover: recorded from r07 on (replica health +
    # journal adoption land between r06 and r07)
    ("fleet_failover_wall_s", "lower", 0.50,
     "fleet kill-1-of-2 failover wall: replica_kill to the last "
     "victim-homed ticket terminal on a survivor (s), moved solves "
     "bit-identical to an uninterrupted twin fleet"),
    ("fleet_failover_lost_requests", "lower_abs", 0.0,
     "requests lost across the fleet failover drill (abs gate: the "
     "zero-loss guarantee is a constant target, any loss regresses)"),
    ("chaos_recover_wall_s", "lower", 0.60,
     "serving kill-and-recover wall: journal replay + persisted "
     "hierarchies + AOT warm start to fully drained (s)"),
    ("chaos_accepted_p99_ms", "lower", 0.60,
     "p99 latency of ADMITTED requests under 2x-saturation shed load "
     "(ms) — must stay within the deadline budget"),
    ("mc_dist_fused_speedup", "higher", 0.25,
     "distributed fused-vs-unfused cycle speedup (MULTICHIP)"),
    ("matrix_free_cycle_speedup", "higher", 0.25,
     "matrix-free vs slab warm V-cycle speedup (GEO 128^3 paired "
     "replay, bench.py matfree — constant-coefficient levels drop "
     "the DIA value-slab operand)"),
    ("matrix_free_level_bytes_ratio", "lower", 0.25,
     "summed per-level operator solve-data bytes, matrix-free over "
     "slab build (bench.py matfree; lower = more of the hierarchy "
     "serves from O(k) stencil coefficients)"),
    # ISSUE 20 Krylov-shell fusion: recorded from r07 on (the
    # spmv+dot / cg_update shell kernels land after the autotuner
    # round). Off-TPU rigs record ~1.0x (the kernels decline to the
    # identical-expression XLA fallback), so the tolerance brackets
    # rig noise around that floor until the TPU rounds take over
    ("krylov_fused_speedup", "higher", 0.25,
     "fused vs unfused Krylov-shell warm solve speedup (bench.py "
     "krylov — paired krylov_fusion=1/0 replay of PCG + GEO AMG on "
     "the flagship 128^3 shape; the spmv+p.Ap and cg_update+r.r "
     "single-pass kernels plus the cycle-borne r.z epilogue)"),
    # ISSUE 19 online autotuner: recorded from r06 on (the
    # shadow-solve config search lands after the matrix-free round)
    ("autotune_speedup", "higher", 0.30,
     "mistuned hot fingerprint re-served after shadow-validated "
     "promotion, min of iteration and exec-wall ratios (bench.py "
     "autotune; gate >= 2x on both)"),
    ("autotune_shadow_p99_impact_pct", "lower_abs", 2.0,
     "paired lockstep saturated-burst p99 delta, autotune on vs off "
     "(abs pct gate: shadows use idle capacity only, the target is "
     "0)"),
)

_NUM = r"(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)"
_KV_RE = re.compile(r'"([A-Za-z0-9_^.\-]+)":\s*' + _NUM)


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def _round_id(path: str, wrapper: Dict[str, Any],
              payload: Optional[Dict[str, Any]]) -> Optional[int]:
    """Stable round key: the artifact's own `round` stamp (bench.py
    schema_version >= 2) outranks the driver wrapper's `n`, which
    outranks filename digits (the legacy fallback)."""
    if payload is not None:
        r = payload.get("round")
        if isinstance(r, int):
            return r
        if isinstance(r, str) and r.isdigit():
            return int(r)
    n = wrapper.get("n")
    if isinstance(n, int):
        return n
    m = re.search(r"_r0*(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else None


def _scalars_from_tail(tail: str) -> Dict[str, float]:
    """Recover scalar `"key": number` pairs from a wrapper's captured
    stdout tail — the r05 path, where the full one-line JSON outgrew
    the driver's bounded capture and `parsed` came back null. Partial
    pairs at the truncation boundary simply don't match."""
    out: Dict[str, float] = {}
    for m in _KV_RE.finditer(tail or ""):
        try:
            out[m.group(1)] = float(m.group(2))
        except ValueError:      # pragma: no cover - regex admits floats
            pass
    return out


def load_round(path: str, kind: str) -> Optional[Dict[str, Any]]:
    """One wrapper file -> {"round", "kind", "file", "source",
    "metrics": {name: value}} or None when it contributes nothing.
    Raises on unreadable/malformed JSON (the --smoke failure mode)."""
    with open(path) as f:
        wrapper = json.load(f)
    if not isinstance(wrapper, dict):
        raise ValueError(f"{os.path.basename(path)}: wrapper is not a "
                         f"JSON object")
    payload = wrapper.get("parsed")
    metrics: Dict[str, float] = {}
    source = "parsed"
    if isinstance(payload, dict):
        extra = payload.get("extra")
        if isinstance(extra, dict):
            for k, v in extra.items():
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    metrics[k] = float(v)
        for k in ("value", "vs_baseline"):
            v = payload.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                metrics[f"headline_{k}"] = float(v)
    else:
        payload = None
        source = "tail"
        metrics = _scalars_from_tail(wrapper.get("tail", ""))
    if kind == "multichip":
        # MULTICHIP metric names are namespaced so the two artifact
        # families can never collide in one series
        metrics = {f"mc_{k}": v for k, v in metrics.items()}
    rid = _round_id(path, wrapper, payload)
    if rid is None or not metrics:
        return None
    return {"round": rid, "kind": kind,
            "file": os.path.basename(path), "source": source,
            "metrics": metrics}


# standalone phase artifacts that may carry series of their own: a
# `python bench.py serving` / `python bench.py fleet` run recorded
# under AMGX_BENCH_ROUND stamps its artifact with `round` + an
# `extra` dict of series-named scalars, contributing them to the
# round even when no BENCH_r<NN>.json wrapper did
PHASE_ARTIFACTS: Tuple[str, ...] = ("BENCH_serving.json",
                                    "BENCH_fleet.json",
                                    "BENCH_matfree.json",
                                    "BENCH_autotune.json",
                                    "BENCH_krylov.json")


def load_phase_artifact(path: str) -> Optional[Dict[str, Any]]:
    """One phase artifact -> the load_round record shape, or None when
    it contributes nothing (no `round` stamp — a standalone run
    outside the driver — or no `extra` scalars). Raises on unreadable
    JSON (the --smoke failure mode for a PRESENT artifact)."""
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict):
        raise ValueError(f"{os.path.basename(path)}: artifact is not "
                         f"a JSON object")
    rid = payload.get("round")
    if isinstance(rid, str) and rid.isdigit():
        rid = int(rid)
    if not isinstance(rid, int) or isinstance(rid, bool):
        return None
    extra = payload.get("extra")
    metrics = {k: float(v) for k, v in extra.items()
               if isinstance(v, (int, float))
               and not isinstance(v, bool)} \
        if isinstance(extra, dict) else {}
    if not metrics:
        return None
    return {"round": rid, "kind": "phase",
            "file": os.path.basename(path), "source": "artifact",
            "metrics": metrics}


def load_rounds(root: str) -> List[Dict[str, Any]]:
    rounds: List[Dict[str, Any]] = []
    # phase artifacts load FIRST: a future wrapper round carrying the
    # same keys overwrites them (build_history merges in list order,
    # wrappers are the driver's authoritative record)
    for name in PHASE_ARTIFACTS:
        path = os.path.join(root, name)
        if os.path.exists(path):
            r = load_phase_artifact(path)
            if r is not None:
                rounds.append(r)
    for kind, pat in (("bench", "BENCH_r*.json"),
                      ("multichip", "MULTICHIP_r*.json")):
        for path in sorted(glob.glob(os.path.join(root, pat))):
            r = load_round(path, kind)
            if r is not None:
                rounds.append(r)
    return rounds


# ---------------------------------------------------------------------------
# history + regression detection
# ---------------------------------------------------------------------------


def build_history(rounds: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-file rounds into one round-keyed trend store. Tracked
    series carry their catalog declaration; every other scalar the
    artifacts recorded is kept under `extra_metrics` (the catalog can
    adopt it later without re-mining old rounds)."""
    by_round: Dict[int, Dict[str, float]] = {}
    files: Dict[int, List[str]] = {}
    for r in rounds:
        by_round.setdefault(r["round"], {}).update(r["metrics"])
        files.setdefault(r["round"], []).append(r["file"])
    ordered = sorted(by_round)
    series: Dict[str, Any] = {}
    for name, direction, tol, doc in SERIES:
        points = [{"round": rid, "value": by_round[rid][name]}
                  for rid in ordered if name in by_round[rid]]
        series[name] = {"direction": direction, "tolerance": tol,
                        "doc": doc, "points": points}
    tracked = {name for name, *_ in SERIES}
    extra = {rid: {k: v for k, v in by_round[rid].items()
                   if k not in tracked}
             for rid in ordered}
    return {
        "schema_version": HISTORY_SCHEMA_VERSION,
        "rounds": [{"round": rid, "files": sorted(files[rid])}
                   for rid in ordered],
        "series": series,
        "extra_metrics": extra,
    }


def detect_regressions(history: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Latest-vs-best-of-prior per tracked series. A series is judged
    only when its latest point lands on the GLOBALLY latest round — a
    series that stopped being recorded (a truncated tail, a skipped
    phase) is stale, not regressed, and must not flag forever; it is
    re-judged the round it reappears. `lower_abs` series gate on an
    absolute bound instead (their target is a constant, not the
    trend's best). At least one prior point is needed either way."""
    out: List[Dict[str, Any]] = []
    latest_round = (history["rounds"][-1]["round"]
                    if history["rounds"] else None)
    for name, s in history["series"].items():
        pts = s["points"]
        if not pts:
            continue
        direction, tol = s["direction"], s["tolerance"]
        latest = pts[-1]
        if latest["round"] != latest_round:
            continue            # stale series (see docstring)
        if direction == "lower_abs":
            if not pts[:-1]:
                continue        # a history of one round judges nothing
            if abs(latest["value"]) > tol:
                out.append({
                    "metric": name, "round": latest["round"],
                    "value": latest["value"], "best_prior": None,
                    "best_prior_round": None,
                    "tolerance": tol,
                    "detail": f"|{latest['value']:g}| exceeds the "
                              f"absolute bound {tol:g}"})
            continue
        prior = pts[:-1]
        if not prior:
            continue
        if direction == "lower":
            best = min(prior, key=lambda p: p["value"])
            worse = latest["value"] > best["value"] * (1.0 + tol)
        else:
            best = max(prior, key=lambda p: p["value"])
            worse = latest["value"] < best["value"] * (1.0 - tol)
        if worse:
            ratio = (latest["value"] / best["value"]
                     if best["value"] else float("inf"))
            out.append({
                "metric": name, "round": latest["round"],
                "value": latest["value"],
                "best_prior": best["value"],
                "best_prior_round": best["round"],
                "tolerance": tol,
                "detail": f"r{latest['round']:02d} "
                          f"{latest['value']:g} vs best-of-prior "
                          f"{best['value']:g} (r{best['round']:02d}), "
                          f"{ratio:.2f}x, tolerance "
                          f"{'+' if direction == 'lower' else '-'}"
                          f"{100 * tol:.0f}%"})
    return out


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def render_markdown(history: Dict[str, Any],
                    regressions: List[Dict[str, Any]]) -> str:
    rids = [r["round"] for r in history["rounds"]]
    flagged = {r["metric"] for r in regressions}
    lines = [
        "# Bench history",
        "",
        "Auto-generated by `tools/bench_history.py` from the "
        "checked-in `BENCH_r*.json` / `MULTICHIP_r*.json` round "
        "artifacts. Do not edit; re-run the tool.",
        "",
        "| series | " + " | ".join(f"r{rid:02d}" for rid in rids)
        + " | status |",
        "|---|" + "---|" * (len(rids) + 1),
    ]
    for name, s in history["series"].items():
        vals = {p["round"]: p["value"] for p in s["points"]}
        cells = []
        for rid in rids:
            v = vals.get(rid)
            cells.append("—" if v is None else f"{v:g}")
        status = "**REGRESSED**" if name in flagged else (
            "ok" if s["points"] else "no data")
        arrow = {"lower": "↓", "higher": "↑",
                 "lower_abs": "→0"}[s["direction"]]
        lines.append(f"| `{name}` {arrow} | " + " | ".join(cells)
                     + f" | {status} |")
    lines.append("")
    if regressions:
        lines.append("## Regressions (latest vs best-of-prior)")
        lines.append("")
        for r in regressions:
            lines.append(f"- `{r['metric']}`: {r['detail']}")
        lines.append("")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def run(root: str = ROOT, write: bool = True
        ) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    rounds = load_rounds(root)
    history = build_history(rounds)
    regressions = detect_regressions(history)
    history["regressions"] = regressions
    if write:
        with open(os.path.join(root, "BENCH_HISTORY.json"), "w") as f:
            json.dump(history, f, indent=1)
            f.write("\n")
        with open(os.path.join(root, "BENCH_HISTORY.md"), "w") as f:
            f.write(render_markdown(history, regressions))
    return history, regressions


def smoke(root: str = ROOT) -> int:
    """Artifact well-formedness self-check (tier-1-reachable): a
    malformed BENCH wrapper fails the build the round it appears, not
    N rounds later when someone reads the trend. Performance
    regressions deliberately do NOT fail smoke."""
    paths = (sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
             + sorted(glob.glob(os.path.join(root,
                                             "MULTICHIP_r*.json"))))
    errors: List[str] = []
    for path in paths:
        base = os.path.basename(path)
        try:
            kind = "bench" if base.startswith("BENCH") else "multichip"
            load_round(path, kind)
        except Exception as e:
            errors.append(f"{base}: {type(e).__name__}: {e}")
    # phase artifacts are optional (absent = fine, a standalone run
    # without a round stamp = fine) but a PRESENT one must parse
    for name in PHASE_ARTIFACTS:
        path = os.path.join(root, name)
        if not os.path.exists(path):
            continue
        try:
            load_phase_artifact(path)
        except Exception as e:
            errors.append(f"{name}: {type(e).__name__}: {e}")
    history = {"rounds": [], "series": {}}
    if not errors:
        history, _reg = run(root, write=False)
        if paths and not history["rounds"]:
            errors.append("no round contributed any metrics "
                          "(extraction broken?)")
    n_series = sum(1 for s in history["series"].values()
                   if s["points"])
    for e in errors:
        print(f"bench_history --smoke: {e}")
    if errors:
        print(f"bench_history --smoke: {len(errors)} problem(s)")
        return 1
    print(f"bench_history --smoke: OK ({len(paths)} artifact(s), "
          f"{len(history['rounds'])} round(s), {n_series} populated "
          f"series)")
    return 0


def main(argv: List[str]) -> int:
    root = ROOT
    if "--root" in argv:
        root = argv[argv.index("--root") + 1]
    if "--smoke" in argv:
        return smoke(root)
    history, regressions = run(root)
    n_series = sum(1 for s in history["series"].values()
                   if s["points"])
    print(f"bench_history: {len(history['rounds'])} round(s), "
          f"{n_series}/{len(SERIES)} series populated -> "
          f"BENCH_HISTORY.json / BENCH_HISTORY.md")
    if regressions:
        for r in regressions:
            print(f"bench_history: REGRESSION {r['metric']}: "
                  f"{r['detail']}")
        return 1
    print("bench_history: no tracked series regressed beyond "
          "tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
