#!/usr/bin/env python
"""Flight-recorder postmortem reader.

Pretty-prints a crash-surviving flight-recorder log
(telemetry/flightrec.py: the `flightrec_dir` knob /
AMGX_TPU_FLIGHTREC_DIR env) and, given the dead service's journal
directory, correlates the event trail with the journaled requests —
the two halves of a postmortem: the journal says WHAT was in flight,
the flight recorder says WHY the process was doing what it was doing
when it died.

Usage:
    python tools/flightrec.py LOGDIR [--journal DIR] [--last N]
                              [--trace ID] [--kind PREFIX]

Reads are corruption-tolerant (torn final lines are dropped and
counted), so this works on the log of a process that died mid-write —
that is the point.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

from amgx_tpu.telemetry.flightrec import FlightRecorder, format_event  # noqa: E402


def load_journal_index(jdir: str) -> List[Dict[str, Any]]:
    """The journal's meta records (req-*.json), corrupt ones skipped —
    the same tolerance discipline as the journal's own open."""
    recs = []
    try:
        names = sorted(os.listdir(jdir))
    except OSError:
        return recs
    for name in names:
        if not (name.startswith("req-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(jdir, name)) as f:
                meta = json.load(f)
            recs.append(meta)
        except Exception:
            continue
    return recs


def correlate(events: List[Dict[str, Any]],
              journal: List[Dict[str, Any]]) -> List[str]:
    """Per journaled request: its status + every flight event stamped
    with its trace id (the trace id is the join key — the journal
    persists it exactly so a postmortem can do this)."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        tr = e.get("trace")
        if tr:
            by_trace.setdefault(str(tr), []).append(e)
    lines = []
    for meta in sorted(journal, key=lambda m: int(m.get("seq", 0))):
        tr = meta.get("trace")
        lines.append(
            f"request {meta.get('id')} [{meta.get('status')}] "
            f"tenant={meta.get('tenant')} "
            f"fingerprint={str(meta.get('fingerprint'))[:24]} "
            f"trace={tr or '-'}")
        for e in by_trace.get(str(tr), []) if tr else []:
            lines.append("    " + format_event(e))
    orphans = [e for e in events
               if e.get("trace")
               and not any(str(m.get("trace")) == str(e["trace"])
                           for m in journal)]
    if orphans:
        lines.append(f"({len(orphans)} trace-stamped events match no "
                     f"journal record — completed+pruned or "
                     f"pre-journal requests)")
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("logdir", help="flight-recorder directory")
    ap.add_argument("--journal", help="solve-journal directory to "
                                      "correlate against")
    ap.add_argument("--last", type=int, default=None,
                    help="only the last N events")
    ap.add_argument("--trace", default=None,
                    help="only events stamped with this trace id")
    ap.add_argument("--kind", default=None,
                    help="only events whose kind starts with this")
    args = ap.parse_args(argv)
    events = FlightRecorder.load(args.logdir)
    if args.trace:
        events = [e for e in events if e.get("trace") == args.trace]
    if args.kind:
        events = [e for e in events
                  if str(e.get("kind", "")).startswith(args.kind)]
    if args.last:
        events = events[-args.last:]
    print(f"flight recorder @ {args.logdir}: {len(events)} event(s)")
    for e in events:
        print("  " + format_event(e))
    if args.journal:
        journal = load_journal_index(args.journal)
        print(f"\njournal correlation @ {args.journal}: "
              f"{len(journal)} record(s)")
        for line in correlate(events, journal):
            print("  " + line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
