#!/usr/bin/env python
"""Static span- and metric-registry checker.

Three contracts guard the telemetry subsystem's honesty, and all are
checkable without running anything:

1. REGISTRY COVERAGE — every span name used in the package (a string or
   f-string literal passed to `trace_region(...)` / `span(...)`) must
   match a pattern declared in `telemetry.spans.DECLARED_SPANS`. A
   typo'd region name would otherwise silently fork a new time series
   (and, under `amg.*`, silently leak out of the accounted fraction).

2. LEAF DISJOINTNESS — the declared patterns under the accounted prefix
   (`amg.*`) must be pairwise NON-NESTING: `profiling.timers_total`
   sums them flat, so a declared span that is an ancestor of another
   declared span would double-count its child's wall time and the PR-3
   `setup_accounted_fraction >= 0.9` contract would silently report
   fractions > honest.

3. METRIC-NAME COVERAGE — every literal metric name recorded through
   the registry (`_tm.inc(...)` / `metrics.observe(...)` /
   `set_gauge` / `max_gauge` on the package's conventional receivers)
   must be declared in the matching catalog
   (telemetry.metrics.COUNTERS / GAUGES / HISTOGRAMS). The registry
   raises at runtime too, but only when the line executes — this
   catches the typo'd counter in the error path nobody exercised.
   Non-literal names (the serving cache's configurable counter map)
   are skipped: the runtime check owns those.

4. NO DEAD METRICS — the REVERSE of 3: every name in the DECLARED
   catalogs must have at least one recording site in the package — a
   literal receiver call, an f-string receiver call whose wildcard
   pattern covers it (`_tm.inc(f"resilience.fallback.{action}")`
   keeps the whole family alive), or a plain string constant equal to
   the name (the indirected counter maps the serving cache threads
   through). Docstrings don't count. Catches catalog rot: a metric
   whose last increment site was refactored away would otherwise keep
   being exported as an eternally-zero series that LOOKS like
   instrumentation.

f-string placeholders (`{expr}`) are normalized to `*`, so
`f"amg.L{k}.galerkin"` checks as `amg.L*.galerkin`. Calls whose name is
not a literal cannot be checked statically and are reported (there are
deliberately none in the package).

Exit code 0 = clean; 1 = violations (printed one per line). Wired into
the test suite by tests/test_telemetry.py.
"""
from __future__ import annotations

import ast
import fnmatch
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

PKG = os.path.join(_ROOT, "amgx_tpu")

# the recording engine itself (generic `name` parameters, the decorator
# helper): it defines the machinery, it does not USE span names
_EXEMPT = (
    os.path.join("amgx_tpu", "profiling.py"),
    os.path.join("amgx_tpu", "telemetry", "spans.py"),
)

# _tspan/_tmark are the serving layer's knob-gated wrappers; their
# call sites carry the literal lifecycle names (the wrappers' own
# forwarding bodies use the checker-invisible _raw aliases, like the
# engine in the exempt spans.py)
_CALL_NAMES = {"trace_region", "span", "mark", "record_span",
               "_tspan", "_tmark"}

# metric-recording surface: attribute calls on the package's
# conventional registry receivers (`_tm.inc(...)`, `metrics.observe`).
# Receiver-qualified on purpose: other objects legitimately own methods
# with these names (determinism.DeterminismChecker.observe)
_METRIC_RECEIVERS = {"_tm", "metrics", "_metrics"}
_METRIC_KINDS = {"inc": "counter", "set_gauge": "gauge",
                 "max_gauge": "gauge", "observe": "histogram",
                 "quantile": "histogram"}
_METRIC_EXEMPT = (
    os.path.join("amgx_tpu", "telemetry", "metrics.py"),
)


def _call_name(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _normalize(node):
    """A Call's first argument as a wildcard pattern: plain string
    literals pass through, f-string placeholders become '*', anything
    else returns None (not statically checkable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:                       # FormattedValue
                parts.append("*")
        return "".join(parts)
    return None


def extract_span_literals(root: str = PKG):
    """(file, line, normalized_name) for every span-name use; name is
    None for calls whose argument is not a (f-)string literal. AST-
    based, so docstrings and comments never false-positive."""
    out = []
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, _ROOT)
            if rel in _EXEMPT:
                continue
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) \
                        or _call_name(node) not in _CALL_NAMES \
                        or not node.args:
                    continue
                out.append((path, node.lineno, _normalize(node.args[0])))
    return out


def extract_metric_literals(root: str = PKG):
    """(file, line, kind, name) for every literal metric name recorded
    through the registry's conventional receivers. Dynamic names
    (variables threaded through a config map) are skipped — the
    runtime registry's did-you-mean raise owns those."""
    return _extract_metric_calls(root)[0]


# the RECORDING half of the receiver surface (quantile is a read —
# contract 3 checks its name, contract 4 must not count it as a site)
_WRITE_ATTRS = {"inc", "set_gauge", "max_gauge", "observe"}


def _extract_metric_calls(root: str = PKG):
    """(literals, patterns, writes): literal receiver-call names as
    before; the f-string WRITE calls normalized to wildcard patterns
    (`f"resilience.fallback.{action}"` -> 'resilience.fallback.*');
    and the (kind, name) literal WRITE sites — contract 4's evidence
    that a metric (family) has a live recording site."""
    literals, patterns, writes = [], [], []
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, _ROOT)
            if rel in _METRIC_EXEMPT:
                continue
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                f_ = node.func
                if not (isinstance(f_, ast.Attribute)
                        and f_.attr in _METRIC_KINDS
                        and isinstance(f_.value, ast.Name)
                        and f_.value.id in _METRIC_RECEIVERS):
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    literals.append((path, node.lineno,
                                     _METRIC_KINDS[f_.attr], arg.value))
                    if f_.attr in _WRITE_ATTRS:
                        writes.append((_METRIC_KINDS[f_.attr],
                                       arg.value))
                elif isinstance(arg, ast.JoinedStr) \
                        and f_.attr in _WRITE_ATTRS:
                    pat = _normalize(arg)
                    if pat is not None:
                        patterns.append((path, node.lineno,
                                         _METRIC_KINDS[f_.attr], pat))
    return literals, patterns, writes


def extract_string_constants(root: str = PKG):
    """Every non-docstring string constant in the package — contract
    4's fallback evidence for metric names threaded through
    indirection (the serving cache's counter map). Exact-equality
    matching only, so a name mentioned inside a prose sentence never
    counts."""
    out = set()
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, _ROOT)
            if rel in _METRIC_EXEMPT:
                continue
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            docstrings = set()
            for node in ast.walk(tree):
                if isinstance(node, (ast.Module, ast.ClassDef,
                                     ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    body = getattr(node, "body", [])
                    if body and isinstance(body[0], ast.Expr) \
                            and isinstance(body[0].value, ast.Constant) \
                            and isinstance(body[0].value.value, str):
                        docstrings.add(id(body[0].value))
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and id(node) not in docstrings:
                    out.add(node.value)
    return out


def _compatible(used: str, declared: str) -> bool:
    """Could the used (possibly wildcarded) name match the declared
    pattern? A used '*' is an f-string placeholder — a solver name or
    a level index, assumed DOT-FREE (every placeholder in the package
    substitutes an identifier/number), so segment counts must agree
    and comparison is per dot-segment. The used name's LITERAL
    segments and the literal prefix/suffix around its placeholders
    must fit the declared pattern exactly — a typo in any literal part
    ('*.solv', 'amg.L*.stregth') fails against every declared entry.
    Exact fnmatch for the fully-literal case."""
    if "*" not in used:
        return fnmatch.fnmatchcase(used, declared)
    us, ds = used.split("."), declared.split(".")
    if len(us) != len(ds):
        return False            # placeholders never contain dots
    for u, d in zip(us, ds):
        if "*" in u:
            # unknown placeholder content: compatible when the
            # declared segment is itself a wildcard, or the used
            # segment's literal prefix/suffix around '*' fits the
            # declared literal
            if "*" in d:
                continue
            pre, _, suf = u.partition("*")
            if not (d.startswith(pre) and d.endswith(suf)):
                return False
        elif not fnmatch.fnmatchcase(u, d):
            return False
    return True


def check():
    from amgx_tpu.telemetry import spans as S

    errors = []

    # 1. registry coverage
    for path, line, name in extract_span_literals():
        rel = os.path.relpath(path, _ROOT)
        if name is None:
            errors.append(f"{rel}:{line}: span name is not a string "
                          f"literal (cannot be checked statically)")
            continue
        if not any(_compatible(name, d) for d in S.DECLARED_SPANS):
            errors.append(f"{rel}:{line}: span {name!r} matches no "
                          f"declared pattern (telemetry/spans.py "
                          f"DECLARED_SPANS)")

    # 2. accounted-leaf disjointness: concretize '*' and require that
    # no declared amg.* pattern is a dotted ancestor of another
    acc = [d for d in S.DECLARED_SPANS
           if d.startswith(S.ACCOUNTED_PREFIX)]
    conc = {d: d.replace("*", "X") for d in acc}
    for a in acc:
        for b in acc:
            if a != b and conc[b].startswith(conc[a] + "."):
                errors.append(
                    f"declared span {a!r} is an ancestor of {b!r}: "
                    f"the accounted amg.* sum would double-count")

    # 3. metric-name coverage: literal names recorded through the
    # registry must be declared in the matching catalog
    from amgx_tpu.telemetry import metrics as M
    catalogs = {"counter": M.COUNTERS, "gauge": M.GAUGES,
                "histogram": M.HISTOGRAMS}
    literals, patterns, writes = _extract_metric_calls()
    for path, line, kind, name in literals:
        rel = os.path.relpath(path, _ROOT)
        if name not in catalogs[kind]:
            errors.append(
                f"{rel}:{line}: {kind} {name!r} is not declared in "
                f"telemetry/metrics.py "
                f"({'COUNTERS' if kind == 'counter' else 'GAUGES' if kind == 'gauge' else 'HISTOGRAMS'})")

    # 4. no dead metrics: every declared name needs a recording site —
    # a literal call of the right WRITE kind, an f-string call whose
    # wildcard covers it, or (indirection fallback) an exact string
    # constant anywhere outside a docstring. `quantile` is a read, not
    # a recording site.
    write_kinds = {"counter", "gauge", "histogram"}
    lit_by_kind = {k: set() for k in write_kinds}
    for kind, name in writes:
        lit_by_kind[kind].add(name)
    pat_by_kind = {k: set() for k in write_kinds}
    for path, line, kind, pat in patterns:
        pat_by_kind[kind].add(pat)
    constants = None      # lazily built: most names resolve earlier
    for kind, catalog in catalogs.items():
        for name in catalog:
            if name in lit_by_kind[kind]:
                continue
            if any(fnmatch.fnmatchcase(name, p)
                   for p in pat_by_kind[kind]):
                continue
            if constants is None:
                constants = extract_string_constants()
            if name in constants:
                continue
            errors.append(
                f"dead metric: declared {kind} {name!r} has no "
                f"increment/observe site in the package (catalog rot "
                f"— remove the declaration or restore the "
                f"instrumentation)")
    return errors


def main() -> int:
    errors = check()
    if errors:
        for e in errors:
            print(e)
        print(f"check_spans: {len(errors)} violation(s)")
        return 1
    print("check_spans: OK (span-registry coverage + accounted-leaf "
          "disjointness + metric-name coverage + no dead metrics)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
