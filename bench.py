"""Benchmark entry point (run on the real TPU chip by the driver).

Writes the FULL results payload to the `BENCH.json` artifact file and
prints ONE COMPACT JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "artifact": "BENCH.json", "extra": {scalar headline keys only}}

The stdout line carries only scalar keys (no nested breakdowns): the
driver captures a bounded stdout tail, and round 5 lost its entire
parse (`BENCH_r05.json parsed: null`) because the one-line JSON with
every per-level breakdown outgrew that capture. Breakdowns, spreads
and per-phase dictionaries live in BENCH.json, which loads with a
plain `json.load`.

The optional 256^3 north-star phase runs only when the headline phase
left wall-clock budget, and under a SIGALRM guard, so the line always
prints.

Headline: 7-pt Poisson 128^3 (2.1M rows) solved to a TRUE 1e-8 relative
residual in full f64 accuracy — BASELINE.md milestone 3 scaled to one
chip — using the TPU-native flagship configuration: REFINEMENT (f64
defect correction) around FGMRES + GEO-aggregation AMG running f32
(every level banded/DIA via the Pallas SpMV kernel, reshape transfer
operators, dense-QR coarse solve).

`vs_baseline` is measured against the reference's roofline on its own
hardware: AmgX SpMV is HBM-bandwidth-bound, so we report our achieved
SpMV bandwidth as a fraction of A100 peak (1555 GB/s) — the honest
single-chip proxy until a side-by-side A100 run exists (the reference
repo publishes no benchmark tables, BASELINE.md).
"""
from __future__ import annotations

import json
import time

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/amgx_tpu_jax_cache_tpu")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import amgx_tpu as amgx  # noqa: E402
from amgx_tpu.config import Config  # noqa: E402

A100_HBM_GBPS = 1555.0  # A2 SXM A100-40GB peak memory bandwidth

from amgx_tpu.presets import FLAGSHIP  # noqa: E402


def bench_spmv_vs_ceiling(n: int = 128, reps: int = 50, samples: int = 9):
    """SpMV GB/s on 7-pt Poisson n^3 (DIA layout, float32: the
    bandwidth-bound regime the reference's csrmv lives in), measured
    against the plain-XLA streaming ceiling of the same rig in the SAME
    pass: the tunnel's effective bandwidth fluctuates 2x run to run, so
    the two loops are timed interleaved, best-of-N each, and the ratio —
    not either absolute number — is the stable efficiency metric."""
    A = amgx.gallery.poisson("7pt", n, n, n, dtype=np.float32).init()
    x = jnp.ones(A.num_rows, jnp.float32)

    @jax.jit
    def spmv_loop(x):
        def body(_, x):
            return amgx.ops.spmv(A, x) * (1.0 / 6.0)
        return jax.lax.fori_loop(0, reps, body, x)

    rows = 256 * 1024 * 1024 // (128 * 4)
    v = jnp.ones((rows, 128), jnp.float32)

    @jax.jit
    def stream_loop(v):
        return jax.lax.fori_loop(0, 10, lambda _, x: x * 1.000001, v)

    spmv_loop(x).block_until_ready()         # compile
    stream_loop(v).block_until_ready()
    # honest bytes model: each value read once, x read once, y written
    # once (the Pallas DIA kernel achieves exactly this traffic)
    n_rows = A.num_rows
    if A.dia_vals is not None:
        k = len(A.dia_offsets)
        bytes_moved = (k * n_rows + 2 * n_rows) * 4
    else:
        bytes_moved = A.ell_cols.size * (4 + 4) + A.num_rows * 4 * 2
    stream_bytes = 2 * rows * 128 * 4
    # the tunnel's effective bandwidth swings 2-3x run to run, which
    # made a best-of-min RATIO oscillate across rounds (0.79/1.20/0.74).
    # Pair each spmv sample with an adjacent stream sample and report
    # the MEDIAN per-pair ratio with its spread — the paired quotient
    # cancels the rig noise the two mins did not share.
    ratios = []
    spmv_dt, stream_dt = float("inf"), float("inf")
    for _ in range(samples):
        t0 = time.perf_counter()
        spmv_loop(x).block_until_ready()
        s_i = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        stream_loop(v).block_until_ready()
        c_i = (time.perf_counter() - t0) / 10
        spmv_dt = min(spmv_dt, s_i)
        stream_dt = min(stream_dt, c_i)
        ratios.append((bytes_moved / s_i) / (stream_bytes / c_i))
    ratios.sort()
    return {
        "gbps": bytes_moved / spmv_dt / 1e9,
        "ms": spmv_dt * 1e3,
        "ceiling_gbps": stream_bytes / stream_dt / 1e9,
        "ratio_median": ratios[len(ratios) // 2],
        "ratio_min": ratios[0],
        "ratio_max": ratios[-1],
    }


def bench_spmv_layouts(n: int = 128, reps: int = 30, swell_n: int = 192):
    """SpMV efficiency phase (`python bench.py spmv`): achieved GB/s
    against the rig's plain-XLA streaming ceiling per layout
    (DIA/ELL/SWELL), plus fused-vs-unfused for the new smoother
    kernels — the tentpole's one-pass claim as a recorded number.

    Bytes models are the honest per-layout minimums: each stored value
    read once, the vectors read/written once. The fused rows time the
    whole presmooth(2 sweeps)+residual pair; `fused_speedup` is the
    wall-clock ratio against the unfused sweep-by-sweep compose of the
    SAME math on the same layout (both jitted, best-of-N), so rig noise
    cancels in the quotient like the spmv/stream pairing above."""
    import dataclasses

    from amgx_tpu.ops import smooth as fused_ops
    from amgx_tpu.ops.batched import smooth_dia_multi  # noqa: F401
    from amgx_tpu.ops.spmv import spmv as _spmv

    rng = np.random.default_rng(11)
    out = {}

    # shared streaming ceiling (one measurement; the per-layout ratios
    # below each pair against an adjacent sample of it)
    rows = 256 * 1024 * 1024 // (128 * 4)
    v = jnp.ones((rows, 128), jnp.float32)

    @jax.jit
    def stream_loop(v):
        return jax.lax.fori_loop(0, 10, lambda _, x: x * 1.000001, v)

    stream_loop(v).block_until_ready()
    stream_bytes = 2 * rows * 128 * 4

    def _time(fn, *args):
        jax.block_until_ready(fn(*args))          # compile
        best, ceil_dt = float("inf"), float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            best = min(best, time.perf_counter() - t0)
            t0 = time.perf_counter()
            stream_loop(v).block_until_ready()
            ceil_dt = min(ceil_dt, time.perf_counter() - t0)
        return best, stream_bytes / ceil_dt / 1e9

    def _loop(op):
        @jax.jit
        def run(x, b):
            def body(_, x):
                return op(x, b)
            return jax.lax.fori_loop(0, reps, body, x)
        return run

    # ---- DIA ----------------------------------------------------------
    A = amgx.gallery.poisson("7pt", n, n, n, dtype=np.float32).init()
    k = len(A.dia_offsets)
    nr = A.num_rows
    x = jnp.ones(nr, jnp.float32)
    b = jnp.ones(nr, jnp.float32)
    dinv = jnp.full((nr,), 1.0 / 6.0, jnp.float32)
    taus = jnp.asarray(np.full(2, 0.9), jnp.float32)

    spmv_dt, ceil = _time(_loop(lambda x, b: _spmv(A, x) * (1 / 6.0)),
                          x, b)
    spmv_bytes = (k + 2) * nr * 4
    out["dia"] = {
        "gbps": round(spmv_bytes * reps / spmv_dt / 1e9, 2),
        "vs_ceiling": round((spmv_bytes * reps / spmv_dt / 1e9) / ceil,
                            3),
    }

    slabs = fused_ops.build_fused_slabs(A, dinv) \
        if fused_ops.fused_runtime_on() else None

    def unfused_pair(x, b):
        xx = x
        for t in range(2):
            xx = xx + (taus[t] * (b - _spmv(A, xx))) * dinv
        return xx, b - _spmv(A, xx)

    if slabs is not None:
        def fused_pair(x, b):
            return fused_ops.dia_fused_smooth(A, slabs, b, x, taus,
                                              dinv=dinv,
                                              with_residual=True)
    else:
        fused_pair = None

    # both loops carry (x, r) through the fori state so XLA cannot
    # dead-code-eliminate the residual half of the pair being measured
    @jax.jit
    def unf_loop(x, b):
        def body(_, st):
            return unfused_pair(st[0], b)
        return jax.lax.fori_loop(0, reps, body, (x, b))

    t_unf, _ = _time(lambda x, b: unf_loop(x, b), x, b)
    # fused ideal bytes: values once + x/b/dinv in + x'/r out
    fused_bytes = (k + 5) * nr * 4
    row = {"unfused_s": round(t_unf / reps, 6)}
    if fused_pair is not None:
        @jax.jit
        def fus_loop(x, b):
            def body(_, st):
                return fused_pair(st[0], b)
            return jax.lax.fori_loop(0, reps, body, (x, b))

        t_fus, ceil2 = _time(lambda x, b: fus_loop(x, b), x, b)
        row.update({
            "fused_s": round(t_fus / reps, 6),
            "fused_speedup": round(t_unf / t_fus, 3),
            "fused_gbps": round(fused_bytes * reps / t_fus / 1e9, 2),
            "fused_vs_ceiling": round(
                (fused_bytes * reps / t_fus / 1e9) / ceil2, 3),
        })
    else:
        row["fused"] = "unavailable (non-TPU rig)"
    out["dia_smooth2_residual"] = row

    # ---- ELL ----------------------------------------------------------
    try:
        A_ell = dataclasses.replace(
            A, dia_offsets=None, dia_vals=None, row_ids=None,
            diag_idx=None, initialized=False).init(ell="always")
        assert A_ell.ell_cols is not None
        t_ell, ceil3 = _time(
            _loop(lambda x, b: _spmv(A_ell, x) * (1 / 6.0)), x, b)
        ell_bytes = (A_ell.ell_cols.size * (4 + 4) + 2 * nr * 4)
        out["ell"] = {
            "gbps": round(ell_bytes * reps / t_ell / 1e9, 2),
            "vs_ceiling": round(
                (ell_bytes * reps / t_ell / 1e9) / ceil3, 3),
        }
    except Exception as e:  # pragma: no cover - bench robustness
        out["ell_error"] = str(e)[:120]

    # ---- SWELL (unstructured path; 2D so the window fits) -------------
    try:
        from amgx_tpu.ops.pallas_swell import build_swell_host
        A2 = amgx.gallery.poisson("9pt", swell_n, swell_n,
                                  dtype=np.float32).init()
        sw = build_swell_host(np.asarray(A2.row_offsets),
                              np.asarray(A2.col_indices),
                              np.asarray(A2.values, np.float32),
                              A2.num_rows, A2.num_cols)
        assert sw is not None
        c4, v4, c0r, nch, w128 = sw
        A_sw = dataclasses.replace(
            A2, dia_offsets=None, dia_vals=None, ell_cols=None,
            ell_vals=None, swell_cols=jnp.asarray(c4),
            swell_vals=jnp.asarray(v4), swell_c0row=jnp.asarray(c0r),
            swell_nchunk=jnp.asarray(nch), swell_w128=int(w128))
        n2 = A_sw.num_rows
        x2 = jnp.ones(n2, jnp.float32)
        b2 = jnp.ones(n2, jnp.float32)
        d2 = jnp.full((n2,), 1.0 / 8.0, jnp.float32)
        t_sw, ceil4 = _time(
            _loop(lambda x, b: _spmv(A_sw, x) * 0.1), x2, b2)
        sw_bytes = v4.size * (4 + 4) + 2 * n2 * 4
        out["swell"] = {
            "gbps": round(sw_bytes * reps / t_sw / 1e9, 2),
            "vs_ceiling": round(
                (sw_bytes * reps / t_sw / 1e9) / ceil4, 3),
        }
        tau1 = jnp.asarray(np.full(1, 0.8), jnp.float32)

        @jax.jit
        def sw_unf(x, b):
            def body(_, x):
                return x + (tau1[0] * (b - _spmv(A_sw, x))) * d2
            return jax.lax.fori_loop(0, reps, body, x)

        t_swu, _ = _time(lambda x, b: sw_unf(x, b), x2, b2)
        row = {"unfused_sweep_s": round(t_swu / reps, 6)}
        if fused_ops.fused_runtime_on():
            @jax.jit
            def sw_fus(x, b):
                def body(_, x):
                    return fused_ops.swell_fused_smooth(
                        A_sw, b, x, tau1, dinv=d2, with_residual=False)
                return jax.lax.fori_loop(0, reps, body, x)

            t_swf, _ = _time(lambda x, b: sw_fus(x, b), x2, b2)
            row.update({
                "fused_sweep_s": round(t_swf / reps, 6),
                "fused_speedup": round(t_swu / t_swf, 3),
            })
        out["swell_smooth_step"] = row
    except Exception as e:  # pragma: no cover - bench robustness
        out["swell_error"] = str(e)[:120]

    # ---- fused-vs-unfused CYCLE (grid transfers + coarse tail) --------
    # One GEO/DIA V-cycle at 64^3 f32: the cycle_fusion knob only
    # changes the trace, so both timings run against one hierarchy
    try:
        cfg = Config.from_string(
            "solver(s)=PCG, s:max_iters=1, s:monitor_residual=1,"
            " s:preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
            " amg:selector=GEO, amg:smoother=CHEBYSHEV_POLY,"
            " amg:chebyshev_polynomial_order=2, amg:presweeps=1,"
            " amg:postsweeps=1, amg:max_iters=1,"
            " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=32")
        Ac = amgx.gallery.poisson("7pt", 64, 64, 64,
                                  dtype=np.float32).init()
        slv = amgx.create_solver(cfg)
        slv.setup(Ac)
        sp = cycle_fused_speedup(slv, jnp.ones(Ac.num_rows, jnp.float32),
                                 reps=9)
        if sp is not None:
            out["geo_cycle_64^3"] = sp
    except Exception as e:  # pragma: no cover - bench robustness
        out["cycle_error"] = str(e)[:120]

    return out


def _amg_of(slv):
    """Walk the preconditioner chain to the AMG hierarchy owner."""
    s = slv
    for _ in range(4):
        if hasattr(s, "amg"):
            return s.amg
        s = getattr(s, "preconditioner", None)
        if s is None:
            break
    return None


def _cycle_kernel_counts(amg, data, b):
    """Per-cycle kernel counts from the traced cycle's jaxpr — the
    HBM-pass regression number the artifact tracks round over round
    (each dia_* site is one single-pass kernel; dia_spmv sites are the
    unfused passes cycle fusion is meant to remove)."""
    import re
    jaxpr = str(jax.make_jaxpr(
        lambda bb, xx: amg.cycle(data, bb, xx))(b, jnp.zeros_like(b)))
    names = re.findall(r"name=\"?([A-Za-z_0-9]+)\"?", jaxpr)
    counts = {}
    for nm in names:
        if "dia" in nm or "swell" in nm:
            counts[nm] = counts.get(nm, 0) + 1
    return counts


def _time_median(fn, args, reps):
    jax.block_until_ready(fn(*args))         # compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def cycle_attribution(slv, b, reps: int = 10):
    """Solve-phase attribution (the solve-side mirror of the setup
    breakdown): per-level rows / stored diagonals / fusion kind /
    measured per-level transfer+smooth pair time, the fused-tail
    boundary, and the traced cycle's per-cycle kernel counts."""
    from amgx_tpu.amg import cycles as _cyc
    from amgx_tpu.ops import smooth as _sm
    amg = _amg_of(slv)
    if amg is None:
        return {"error": "no AMG preconditioner"}
    data = amg.solve_data()
    dt = amg._PRECISIONS[amg.precision]
    bb = b.astype(dt) if dt is not None else b
    out = {"kernels_per_cycle": _cycle_kernel_counts(amg, data, bb)}
    shape = amg.cycle_name if amg.cycle_name in ("V", "W", "F") else "V"
    tail_start = None
    if amg.cycle_fusion:
        for k in range(len(amg.levels)):
            bk = jnp.ones(amg.levels[k].A.num_rows, bb.dtype)
            if _sm.coarse_tail_cycle(amg, shape, data, k, bk,
                                     jnp.zeros_like(bk)) is not None:
                tail_start = k
                break
    out["tail_start_level"] = tail_start
    levels = []
    for lvl, level in enumerate(amg.levels):
        A = level.A
        row = {"level": lvl, "rows": int(A.num_rows),
               "diags": (len(A.dia_offsets) if A.dia_offsets is not None
                         else None)}
        nxt = (amg.levels[lvl + 1].A if lvl + 1 < len(amg.levels)
               else amg.coarsest_A)
        if tail_start is not None and lvl >= tail_start:
            row["kind"] = "vmem_tail"
            if lvl == tail_start:
                bk = jnp.ones(A.num_rows, bb.dtype)
                fn = jax.jit(lambda bb_, xx_: _sm.coarse_tail_cycle(
                    amg, shape, data, tail_start, bb_, xx_))
                row["tail_s"] = round(_time_median(
                    fn, (bk, jnp.zeros_like(bk)), reps), 6)
            levels.append(row)
            continue
        ld = data["levels"][lvl]
        has_xfer = "xfer" in ld
        row["kind"] = ("fused_transfers" if amg.cycle_fusion and has_xfer
                       else "unfused_transfers")
        bk = jnp.ones(A.num_rows, bb.dtype)
        xck = jnp.ones(nxt.num_rows, bb.dtype)
        swp, swq = amg._sweeps(lvl, pre=True), amg._sweeps(lvl, pre=False)

        def pair(bb_, xx_, xc_, level=level, ld=ld, swp=swp, swq=swq):
            x2, bc = _cyc._smooth_restrict(amg, level, ld, bb_, xx_, swp)
            return _cyc._prolongate_smooth(amg, level, ld, bb_, x2, xc_,
                                           swq), bc
        row["pair_s"] = round(_time_median(
            jax.jit(pair), (bk, jnp.zeros_like(bk), xck), reps), 6)
        levels.append(row)
    out["levels"] = levels
    return out


def cycle_fused_speedup(slv, b, reps: int = 10):
    """Fused-vs-unfused cycle wall clock on the SAME hierarchy: the
    cycle_fusion knob only changes the trace, so flipping it re-traces
    the cycle against identical solve data — no second setup."""
    amg = _amg_of(slv)
    if amg is None:
        return None
    data = amg.solve_data()
    dt = amg._PRECISIONS[amg.precision]
    bb = b.astype(dt) if dt is not None else b
    x0 = jnp.zeros_like(bb)

    def timed():
        f = jax.jit(lambda bb_, xx_: amg.cycle(data, bb_, xx_))
        return _time_median(f, (bb, x0), reps)

    t_fused = timed()
    old = amg.cycle_fusion
    amg.cycle_fusion = False
    try:
        t_unf = timed()
    finally:
        amg.cycle_fusion = old
    return {"fused_s": round(t_fused, 6), "unfused_s": round(t_unf, 6),
            "speedup": round(t_unf / max(t_fused, 1e-12), 3)}


def bench_flagship(n: int = 128, tolerance: str = "1e-8", reps: int = 3,
                   light: bool = False):
    """REFINEMENT(FGMRES + GEO-aggregation AMG, f32 inner) on 7-pt
    Poisson n^3, f64 system, true relative residual <= tolerance. Setup
    AND solve run entirely on the TPU (jitted static-shape setup)."""
    from amgx_tpu import profiling
    A = amgx.gallery.poisson("7pt", n, n, n).init()
    b = jnp.ones(A.num_rows)
    flagship = FLAGSHIP.replace("tolerance=1e-8", f"tolerance={tolerance}")
    assert tolerance == "1e-8" or flagship != FLAGSHIP, \
        "FLAGSHIP tolerance literal drifted; fix the replace target"
    def _settle(s):
        # setup dispatches asynchronously (the blocking per-level syncs
        # were deliberately removed); bound the timer by the device
        # completing all setup products, or the number under-reports
        jax.block_until_ready(s.solve_data())

    slv = amgx.create_solver(Config.from_string(flagship))
    t0 = time.perf_counter()
    slv.setup(A)
    _settle(slv)
    setup_cold_s = time.perf_counter() - t0
    # warm setup: what resetup/compile-cached production runs see.
    # setup_breakdown records the per-level per-stage wall clock
    # (selector / galerkin / layout / smoother_setup / ship) so setup
    # regressions are attributable; the amg.* regions are disjoint leaf
    # spans, so their sum over the warm wall is the accounted fraction
    # (contract: >= 0.9 — the device-sync tail is timed too).
    slv2 = amgx.create_solver(Config.from_string(
        (flagship + ", amg:structure_reuse_levels=-1") if light
        else flagship))
    profiling.reset_timers()
    t0 = time.perf_counter()
    slv2.setup(A)
    with profiling.trace_region("amg.device_sync"):
        _settle(slv2)
    setup_s = time.perf_counter() - t0
    breakdown = {k: round(v[1], 4) for k, v in profiling.timers().items()
                 if k.startswith(("amg.", "ship."))}
    accounted = min(1.0, profiling.timers_total("amg.") /
                    max(setup_s, 1e-9))
    # resetup with the structure-reuse path ON (what production
    # coefficient-replace cycles use; hierarchy structure kept, only
    # values recomputed). light mode (256^3): the warm solver serves
    # the resetup too — one fewer full setup inside the alarm window.
    if light:
        slv3 = slv2
    else:
        slv3 = amgx.create_solver(Config.from_string(
            flagship + ", amg:structure_reuse_levels=-1"))
        slv3.setup(A)
        _settle(slv3)
    t0 = time.perf_counter()
    slv3.resetup(A)
    _settle(slv3)
    resetup_first_s = time.perf_counter() - t0   # traces the fused plan
    resetup_s = float("inf")                     # steady-state cycles
    for _ in range(2):
        t0 = time.perf_counter()
        slv3.resetup(A)
        _settle(slv3)
        resetup_s = min(resetup_s, time.perf_counter() - t0)
    res = slv2.solve(b)                       # compile
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        res = slv2.solve(b)
        times.append(time.perf_counter() - t0)
    solve_s = sorted(times)[len(times) // 2]
    # solve-phase attribution (the solve-side mirror of the setup
    # breakdown): per-level cycle pair timings + per-cycle kernel
    # counts + fused-vs-unfused cycle wall clock on the same hierarchy
    try:
        cyc_attr = cycle_attribution(slv2, b, reps=max(reps, 5))
        cyc_speed = cycle_fused_speedup(slv2, b, reps=max(reps, 5))
    except Exception as e:  # pragma: no cover - bench robustness
        cyc_attr = {"error": str(e)[:200]}
        cyc_speed = None
    rel = float(
        np.linalg.norm(np.asarray(amgx.ops.residual(A, res.x, b)))
        / np.linalg.norm(np.asarray(b)))
    rap_s, rap_share = _rap_attr(breakdown, setup_s)
    return {
        "setup_cold_s": setup_cold_s,
        "setup_warm_s": setup_s,
        "setup_rows_per_s": A.num_rows / max(setup_s, 1e-9),
        "setup_accounted_fraction": accounted,
        "rap_s": rap_s,
        "rap_share": rap_share,
        "resetup_s": resetup_s,
        "resetup_first_s": resetup_first_s,
        "breakdown": breakdown,
        "solve_s": solve_s,
        "iters": int(res.iterations),
        "converged": bool(res.converged),
        "rel": rel,
        "cycle_breakdown": cyc_attr,
        "cycle_speedup": cyc_speed,
    }


def bench_precision(n: int = 128, reps: int = 3):
    """Mixed-precision phase (`python bench.py precision`): the
    flagship replayed PAIRED at solve_precision=float vs bfloat16 on
    the same system — same REFINEMENT(f64) outer shell, same FGMRES
    inner, only the AMG cycle's operand-slab precision differs (bf16
    slabs stream half the HBM bytes through the fused kernels with
    f32 in-kernel accumulation). Records the per-precision walls, the
    `mixed_precision_speedup` ratio, per-precision iteration counts
    (SolveReport.precision: f64 outer + f32-Krylov inner), and the
    matched-final-residual gate — the bf16 run must still reach the
    f64 relative tolerance, or the speedup is not comparable."""
    # the gate below must track the preset's tolerance (same drift
    # guard as bench_flagship's replace-target assert)
    assert "tolerance=1e-8" in FLAGSHIP, \
        "FLAGSHIP tolerance literal drifted; update bench_precision's " \
        "matched-residual gate"
    tol = 1e-8
    A = amgx.gallery.poisson("7pt", n, n, n).init()
    b = jnp.ones(A.num_rows)
    out = {}
    walls = {}
    for prec in ("float", "bfloat16"):
        slv = amgx.create_solver(Config.from_string(
            FLAGSHIP + f", solve_precision={prec}"))
        slv.setup(A)
        res = slv.solve(b)                     # compile
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            res = slv.solve(b)
            times.append(time.perf_counter() - t0)
        wall = sorted(times)[len(times) // 2]
        walls[prec] = wall
        rel = float(np.max(np.asarray(res.res_norm))
                    / max(np.max(np.asarray(res.norm0)), 1e-300))
        pb = (res.report.precision if res.report is not None
              else None) or {}
        tag = "bf16" if prec == "bfloat16" else prec
        out[f"solve_{tag}_s"] = round(wall, 4)
        out[f"outer_iters_{tag}"] = int(res.iterations)
        out[f"inner_iters_{tag}"] = pb.get("inner_iterations")
        out[f"true_rel_residual_{tag}"] = rel
        out[f"converged_{tag}"] = bool(res.converged)
        out[f"precision_report_{tag}"] = pb
        del slv
    out["mixed_precision_speedup"] = round(
        walls["float"] / max(walls["bfloat16"], 1e-9), 3)
    # matched-residual gate: both precisions reach the flagship's
    # relative tolerance, so the speedup compares equal-quality answers
    out["matched_residuals_ok"] = bool(
        out["converged_float"] and out["converged_bf16"]
        and out["true_rel_residual_bf16"] <= tol)
    return out


def bench_setup(grids=(64, 128)):
    """Setup-only CI phase (`python bench.py setup`): warm hierarchy
    build of the flagship configuration per grid, reporting throughput
    (rows/s) and the attribution contract — the disjoint amg.* region
    sum must account for >= 90% of the warm setup wall so setup
    regressions land in a named bucket, not in invisible residue.
    Emitted into BENCH_*.json so the trajectory catches setup
    regressions, not just solve regressions."""
    from amgx_tpu import profiling
    out = {}
    for n in grids:
        A = amgx.gallery.poisson("7pt", n, n, n).init()
        cold = amgx.create_solver(Config.from_string(FLAGSHIP))
        cold.setup(A)                      # compile + trace warm-up
        jax.block_until_ready(cold.solve_data())
        slv = amgx.create_solver(Config.from_string(FLAGSHIP))
        profiling.reset_timers()
        t0 = time.perf_counter()
        slv.setup(A)
        with profiling.trace_region("amg.device_sync"):
            jax.block_until_ready(slv.solve_data())
        dt = time.perf_counter() - t0
        accounted = min(1.0, profiling.timers_total("amg.")
                        / max(dt, 1e-9))
        breakdown = {k: round(v[1], 4)
                     for k, v in profiling.timers().items()
                     if k.startswith(("amg.", "ship."))}
        rap_s, rap_share = _rap_attr(breakdown, dt)
        out[f"{n}^3"] = {
            "setup_warm_s": round(dt, 3),
            "setup_rows_per_s": round(A.num_rows / max(dt, 1e-9)),
            "setup_accounted_fraction": round(accounted, 3),
            "setup_attribution_ok": bool(accounted >= 0.9),
            "rap_s": rap_s,
            "rap_share": rap_share,
            "breakdown": breakdown,
        }
    return out


import re as _re  # noqa: E402

_RAP_SPAN_RE = _re.compile(r"amg\.L\d+\.(?:rap|rap_plan|rap_values"
                           r"|galerkin)$")


def _rap_attr(breakdown: dict, wall: float):
    """(rap_s, rap_share) of a warm-setup breakdown: the summed
    per-level Galerkin RAP spans — the eager routes (amg.L*.rap /
    amg.L*.galerkin) plus the plan split's structure/value spans
    (amg.L*.rap_plan / amg.L*.rap_values) — over the setup wall. This
    is the attribution field ROADMAP 2(b) asks for: when classical
    setup is still the wall, this number says whether RAP is the
    dominant span or the residue lives elsewhere."""
    rap = sum(v for k, v in breakdown.items() if _RAP_SPAN_RE.match(k))
    return round(rap, 4), round(rap / max(wall, 1e-9), 3)


def bench_spgemm_plan(flagship_n: int = 128, classical_n: int = 64,
                      reps: int = 2):
    """Plan-split RAP phase (`python bench.py spgemm [--smoke]`):
    paired plan-vs-eager WARM-setup replay on the flagship GEO shape
    and the benched classical shape. Both twins run the identical
    config except `spgemm_plan` (1 = structure phase memoized +
    fused/sort-free value phase; 0 = today's eager expand/sort/segment
    composition); each mode pays one cold setup first (compiles +
    plan-cache prime), then the best-of-`reps` warm wall is the
    headline — exactly what a production coefficient-replace cycle
    sees. `spgemm_plan_speedup` (flagship) and
    `spgemm_plan_speedup_classical` are sentinel-tracked."""
    from amgx_tpu.telemetry import metrics as _tm

    def _warm_setup(cfg, A):
        cold = amgx.create_solver(cfg)
        cold.setup(A)
        jax.block_until_ready(cold.solve_data())
        del cold
        best = float("inf")
        for _ in range(reps):
            slv = amgx.create_solver(cfg)
            t0 = time.perf_counter()
            slv.setup(A)
            jax.block_until_ready(slv.solve_data())
            best = min(best, time.perf_counter() - t0)
            del slv
        return best

    out = {}
    cases = (
        (f"flagship_{flagship_n}^3",
         lambda m: Config.from_string(
             FLAGSHIP + f", amg:spgemm_plan={m}"),
         flagship_n),
        (f"classical_{classical_n}^3",
         lambda m: _classical_cfg(extra=f", amg:spgemm_plan={m}"),
         classical_n),
    )
    for label, mk, n in cases:
        A = amgx.gallery.poisson("7pt", n, n, n).init()
        cfg1 = mk("1")
        cold = amgx.create_solver(cfg1)
        cold.setup(A)                  # builds + primes the plan cache
        jax.block_until_ready(cold.solve_data())
        del cold
        # hits counted over the WARM window only (the cold setup
        # builds; it can also hit patterns planned by earlier phases)
        hits0 = int(_tm.get("amg.spgemm.plan_hit"))
        best = float("inf")
        for _ in range(reps):
            slv = amgx.create_solver(cfg1)
            t0 = time.perf_counter()
            slv.setup(A)
            jax.block_until_ready(slv.solve_data())
            best = min(best, time.perf_counter() - t0)
            del slv
        plan_s = best
        hits = int(_tm.get("amg.spgemm.plan_hit")) - hits0
        eager_s = _warm_setup(mk("0"), A)
        out[label] = {
            "plan_warm_setup_s": round(plan_s, 3),
            "eager_warm_setup_s": round(eager_s, 3),
            "plan_hits_per_warm_setup": hits / max(reps, 1),
            "speedup": round(eager_s / max(plan_s, 1e-9), 3),
        }
        del A
    out["spgemm_plan_speedup"] = \
        out[f"flagship_{flagship_n}^3"]["speedup"]
    out["spgemm_plan_speedup_classical"] = \
        out[f"classical_{classical_n}^3"]["speedup"]
    return out


def bench_matfree(n: int = 128, reps: int = 3, smoke: bool = False):
    """Matrix-free GEO phase (`python bench.py matfree [--smoke]`):
    paired replay of the SAME solve with `matrix_free=1` (constant-
    coefficient levels route through ops/stencil.py — SMEM-coefficient
    Pallas kernels on TPU, the XLA masked-coefficient compose on this
    rig) against the `matrix_free=0` slab build. Two sentinel-tracked
    numbers: `matrix_free_cycle_speedup` (warm per-cycle wall, slab
    over matrix-free — higher is better) and
    `matrix_free_level_bytes_ratio` (summed per-level operator
    solve-data bytes, matrix-free over slab — lower is better; the
    fine slab alone is ~7/8 of a 7-pt level's operator stream). Both
    twins must converge in the SAME iteration count — the routing is a
    numerics-preserving form change, so any drift fails the phase."""
    from amgx_tpu.serving.cache import solve_data_bytes
    cfg_s = (
        "solver=FGMRES, max_iters=30, monitor_residual=1,"
        " tolerance=1e-8, gmres_n_restart=20,"
        " convergence=RELATIVE_INI, norm=L2,"
        " preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
        " amg:selector=GEO, amg:smoother=JACOBI_L1,"
        " amg:relaxation_factor=0.75, amg:presweeps=1,"
        " amg:postsweeps=2, amg:max_iters=1, amg:cycle=V,"
        " amg:max_levels=10, amg:min_coarse_rows=32,"
        " amg:matrix_free=")
    A = amgx.gallery.poisson("7pt", n, n, n, dtype=np.float32).init()
    b = jnp.ones(A.num_rows, jnp.float32)
    out = {"grid": f"{n}^3 poisson7pt", "smoke": bool(smoke)}
    walls, iters, lv_bytes = {}, {}, {}
    for mf in ("0", "1"):
        slv = amgx.create_solver(Config.from_string(cfg_s + mf))
        slv.setup(A)
        res = slv.solve(b)                  # compile + warm caches
        iters[mf] = max(int(res.iterations), 1)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(slv.solve(b).x)
            best = min(best, time.perf_counter() - t0)
        walls[mf] = best
        eng = slv
        while not hasattr(eng, "amg"):
            eng = eng.preconditioner
        per = []
        for ld in eng.amg.solve_data()["levels"]:
            smd = ld.get("smoother") or {}
            per.append({
                "rows": int(ld["A"].num_rows),
                "form": "matrix-free" if "stencil" in ld else "slab",
                "operator_bytes": solve_data_bytes(
                    {"A": ld["A"], "stencil": ld.get("stencil"),
                     "dinv": smd.get("dinv")
                     if isinstance(smd, dict) else None}),
            })
        lv_bytes[mf] = per
        out[f"mf{mf}"] = {
            "solve_warm_s": round(best, 4),
            "iters": iters[mf],
            "cycle_warm_s": round(best / iters[mf], 5),
            "levels": per,
        }
        del slv
    assert iters["0"] == iters["1"], (
        f"matrix-free changed convergence: {iters}")
    tot0 = sum(p["operator_bytes"] for p in lv_bytes["0"])
    tot1 = sum(p["operator_bytes"] for p in lv_bytes["1"])
    out["matrix_free_cycle_speedup"] = round(
        walls["0"] / max(walls["1"], 1e-9), 3)
    # 6 decimals: a fully matrix-free hierarchy sits at ~2e-6, which
    # must stay a nonzero "best" for the regression sentinel's
    # relative-tolerance compare
    out["matrix_free_level_bytes_ratio"] = round(
        tot1 / max(tot0, 1), 6)
    out["slab_operator_bytes"] = int(tot0)
    out["matrix_free_operator_bytes"] = int(tot1)
    return out


def _krylov_pass_census(slv, b):
    """Per-iteration HBM-pass census of ONE traced solve_iteration
    (trace-only, kernels routed through the interpreter gate so the
    TPU dispatch decisions are visible on any rig): Pallas kernels by
    name, standalone full-vector reductions outside kernel bodies, and
    the count of full-n-vector operands/results touched by
    compute-bearing leaf eqns (arithmetic/reduction XLA ops plus
    kernel I/O; call wrappers and layout-only plumbing excluded — see
    the walk below) — the n-vector HBM-pass proxy the shell fusion
    cuts."""
    import jax.core as jc
    from amgx_tpu.ops import pallas_spmv as _ps
    with _ps.force_pallas_interpret():
        d = slv.solve_data()
        st = {"x": jnp.zeros_like(b), "r": b}
        st.update(slv.solve_init(d, b, jnp.zeros_like(b), b))
        jaxpr = jax.make_jaxpr(
            lambda dd, ss: slv.solve_iteration(dd, b, ss))(d, st)
    nvec = b.size
    kernels = {}
    for nm in _re.findall(r'name="?([A-Za-z_0-9]+)"?', str(jaxpr)):
        if nm.startswith(("_dia", "_cg")):
            kernels[nm] = kernels.get(nm, 0) + 1

    def subs(eqn):
        for p in eqn.params.values():
            for q in (p if isinstance(p, (tuple, list)) else (p,)):
                if isinstance(q, jc.ClosedJaxpr):
                    yield q.jaxpr
                elif isinstance(q, jc.Jaxpr):
                    yield q

    counts = {"reductions": 0, "passes": 0}
    # call-like wrappers re-bind their operands to an inner jaxpr whose
    # leaf eqns are counted anyway — counting the wrapper boundary too
    # would double-bill every vector that crosses a pjit/scan/custom
    # wrapper (and the fused helpers carry more wrapper layers than the
    # plain composition, so the double-billing is knob-asymmetric)
    wrappers = ("pjit", "closed_call", "custom_jvp_call",
                "custom_vjp_call", "custom_vmap_call", "scan", "while",
                "cond", "remat", "checkpoint")
    # pure layout plumbing is also excluded from the pass count: on
    # XLA:TPU reshape/transpose/broadcast are metadata and the lane-pad
    # dynamic_update_slice copies fuse into their producer, so none of
    # them is an HBM round trip — and the kernel route necessarily
    # carries more of this plumbing (every pallas operand is padded to
    # lane multiples), which would bill the fused knob for free ops
    layout = ("reshape", "transpose", "broadcast_in_dim", "slice",
              "dynamic_slice", "dynamic_update_slice", "pad",
              "squeeze", "concatenate", "convert_element_type",
              "copy")

    def walk(jx):
        for eq in jx.eqns:
            if eq.primitive.name not in wrappers \
                    and eq.primitive.name not in layout:
                counts["passes"] += sum(
                    1 for v in list(eq.invars) + list(eq.outvars)
                    if getattr(v, "aval", None) is not None
                    and v.aval.size >= nvec)
            if eq.primitive.name == "pallas_call":
                continue
            if eq.primitive.name in ("reduce_sum", "reduce_max",
                                     "reduce_min", "dot_general") \
                    and any(getattr(v, "aval", None) is not None
                            and v.aval.size >= nvec
                            for v in eq.invars):
                counts["reductions"] += 1
            for sub in subs(eq):
                walk(sub)

    walk(jaxpr.jaxpr)
    return {"kernels": kernels,
            "standalone_reductions": counts["reductions"],
            "n_vector_passes": counts["passes"]}


def bench_krylov(n: int = 128, reps: int = 3, smoke: bool = False,
                 northstar: bool = True):
    """Krylov-shell phase (`python bench.py krylov [--smoke]`): paired
    replay of the SAME PCG + GEO-aggregation AMG solve with
    `krylov_fusion=1` (the spmv+p.Ap and cg_update+r.r single-pass
    shell kernels plus the cycle-borne r.z epilogue) against `=0` (the
    unfused SpMV + BLAS-1 composition). Sentinel-tracked number:
    `krylov_fused_speedup` (warm solve wall, unfused over fused —
    higher is better). Both twins must converge in the SAME iteration
    count — the shell fusion is a numerics-preserving form change, so
    any drift fails the phase. The artifact also records the
    per-iteration HBM pass census of one traced iteration per knob
    (kernel inventory, standalone full-vector reductions, n-vector
    operand touches). Full mode adds the northstar 256^3 shape on TPU
    (the shape the ROADMAP's 512^3/1024^3 target sits behind); off-TPU
    the kernels decline to the identical-expression XLA fallback, so
    the rig records ~1.0x with the census still proving the TPU
    dispatch."""
    cfg_s = (
        "solver=PCG, max_iters=80, monitor_residual=1,"
        " tolerance=1e-8, convergence=RELATIVE_INI, norm=L2,"
        " preconditioner(amg)=AMG, amg:algorithm=AGGREGATION,"
        " amg:selector=GEO, amg:smoother=JACOBI_L1,"
        " amg:relaxation_factor=0.75, amg:presweeps=1,"
        " amg:postsweeps=2, amg:max_iters=1, amg:cycle=V,"
        " amg:max_levels=10, amg:min_coarse_rows=32,"
        " krylov_fusion=")
    shapes = [n]
    if northstar and not smoke and jax.default_backend() == "tpu":
        shapes.append(256)
    out = {"smoke": bool(smoke)}
    for nn in shapes:
        A = amgx.gallery.poisson("7pt", nn, nn, nn,
                                 dtype=np.float32).init()
        b = jnp.ones(A.num_rows, jnp.float32)
        row = {}
        iters = {}
        walls = {}
        for kf in ("0", "1"):
            slv = amgx.create_solver(Config.from_string(cfg_s + kf))
            slv.setup(A)
            # census BEFORE the first solve: the aggregation level
            # memoizes its fused transfer slabs on first level_data()
            # use, keyed to whether the fused runtime was on at that
            # moment. Tracing under the interpreter gate first memoizes
            # the TPU-shaped structure (coarse tail eligible) — the
            # same structure a real TPU solve would freeze. An off-TPU
            # solve first would memoize slabs=None and the census would
            # report the rig's fallback cycle instead of the dispatch.
            census = _krylov_pass_census(slv, b)
            res = slv.solve(b)              # compile + warm caches
            iters[kf] = max(int(res.iterations), 1)
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(slv.solve(b).x)
                best = min(best, time.perf_counter() - t0)
            walls[kf] = best
            row[f"fusion{kf}"] = {
                "solve_warm_s": round(best, 4),
                "iters": iters[kf],
                "iter_warm_s": round(best / iters[kf], 6),
                "census": census,
            }
            del slv
        assert iters["0"] == iters["1"], (
            f"krylov_fusion changed convergence at {nn}^3: {iters}")
        row["speedup"] = round(walls["0"] / max(walls["1"], 1e-9), 3)
        out[f"{nn}^3"] = row
    head = out[f"{n}^3"]
    out["grid"] = f"{n}^3 poisson7pt"
    out["krylov_fused_speedup"] = head["speedup"]
    out["krylov_fused_passes"] = \
        head["fusion1"]["census"]["n_vector_passes"]
    out["krylov_unfused_passes"] = \
        head["fusion0"]["census"]["n_vector_passes"]
    out["krylov_fused_standalone_reductions"] = \
        head["fusion1"]["census"]["standalone_reductions"]
    return out


def bench_classical(n: int = 64):
    """PCG[f64] + classical PMIS/D2 AMG[f32] (JACOBI_L1) — the
    unstructured-path number the structured flagship does not cover.
    Setup runs through the native host path (amg_host_setup auto: C++
    PMIS / D2 / fused RAP / SWELL builders on numpy-backed levels,
    prefetched to the TPU as they finish); the solve runs the
    windowed-ELL Pallas gather kernel on every unstructured level
    operator and transfer operator (ops/pallas_swell.py).
    amg_precision=float is the reference's dDDI->dDFI mixed-mode
    economics (include/amgx_config.h:102-131): the f64 outer PCG holds
    the true residual. interp_max_elements=4 + max_row_sum=0.9 are the
    reference's own D2 production settings (its flagship classical
    preset, src/configs/FGMRES_CLASSICAL_AGGRESSIVE_PMIS.json).
    Setup is best-of-2: the host path is sensitive to single-core
    scheduler noise on shared rigs."""
    # the literal lives in _classical_cfg so the obs phase replays the
    # SAME config. At 128^3 on TPU the smoother request is
    # MULTICOLOR_DILU: the PR-11 known-fault guard reroutes it to
    # JACOBI_L1 (warned + counted) and the fallback takes the fused
    # classical path — resilience.config_fallback below records the
    # reroute in the bench line. Off-TPU the guard is inert (DILU
    # would actually run), so the CPU rig keeps the JACOBI_L1 literal
    # and its cross-round comparability.
    want_dilu = n >= 128 and jax.default_backend() == "tpu"
    cfg = _classical_cfg("MULTICOLOR_DILU" if want_dilu else
                         "JACOBI_L1")
    from amgx_tpu import profiling
    from amgx_tpu.telemetry import metrics as _tm
    fallback0 = int(_tm.get("resilience.config_fallback"))
    A = amgx.gallery.poisson("7pt", n, n, n).init()
    b = jnp.ones(A.num_rows)
    slv = amgx.create_solver(cfg)
    slv.setup(A)                      # cold (host CPU + compiles)
    jax.block_until_ready(slv.solve_data())
    setup_s = float("inf")
    breakdown = {}
    accounted = 0.0
    for _ in range(2):
        slv2 = amgx.create_solver(cfg)
        profiling.reset_timers()
        t0 = time.perf_counter()
        slv2.setup(A)
        with profiling.trace_region("amg.device_sync"):
            jax.block_until_ready(slv2.solve_data())
        dt = time.perf_counter() - t0
        if dt < setup_s:
            setup_s = dt
            # per-stage attribution of the BEST warm pass (strength /
            # cfsplit / interp / transposeR / rap / layout / ship);
            # amg.* spans are disjoint, so their sum over the wall is
            # the accounted fraction of the warm setup
            breakdown = {
                k: round(v[1], 3) for k, v in profiling.timers().items()
                if k.startswith(("amg.", "ship."))}
            accounted = min(1.0, profiling.timers_total("amg.")
                            / max(dt, 1e-9))
    res = slv2.solve(b)               # compile
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        res = slv2.solve(b)
        times.append(time.perf_counter() - t0)
    solve_s = sorted(times)[len(times) // 2]
    rel = float(
        np.linalg.norm(np.asarray(amgx.ops.residual(A, res.x, b)))
        / np.linalg.norm(np.asarray(b)))
    amg = slv2.preconditioner.amg
    effective = amg.levels[0].smoother.name if amg.levels else "?"
    rap_s, rap_share = _rap_attr(breakdown, setup_s)
    return {
        "setup_warm_s": setup_s,
        "setup_rows_per_s": A.num_rows / max(setup_s, 1e-9),
        "setup_accounted_fraction": accounted,
        "rap_s": rap_s,
        "rap_share": rap_share,
        "breakdown": breakdown,
        "solve_s": solve_s,
        "iters": int(res.iterations),
        "rel": rel,
        # fallback visibility (PR-11 DILU guard): how many hierarchy
        # builds rerouted their smoother, what was asked, what ran
        "config_fallback": int(_tm.get("resilience.config_fallback"))
        - fallback0,
        "smoother_requested": "MULTICOLOR_DILU" if want_dilu
        else "JACOBI_L1",
        "smoother_effective": effective,
    }


def bench_batched(n: int = 32, batch_sizes=(1, 8, 32), reps: int = 3):
    """Batched-serving phase (amgx_tpu/batch/): per-system throughput of
    the vmapped multi-RHS solve at several batch sizes on the n^3 7-pt
    Poisson gallery. The figure of merit is solves/s per batch size —
    the curve shows how much of a single solve's cost the batch
    amortizes (one trace, one dispatch, shared matrix data). Returns
    {batch: {"solves_per_s": ..., "solve_s": ..., "iters": ...}}."""
    from amgx_tpu.batch import BatchedSolver
    from amgx_tpu.presets import BATCHED_CG
    A = amgx.gallery.poisson("7pt", n, n, n).init()
    rng = np.random.default_rng(7)
    out = {}
    bs = BatchedSolver(Config.from_string(BATCHED_CG))
    bs.setup(A)
    for nb in batch_sizes:
        B = jnp.asarray(rng.standard_normal((nb, A.num_rows)))
        res = bs.solve_many(B)                    # compile
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            res = bs.solve_many(B)
            times.append(time.perf_counter() - t0)
        dt = sorted(times)[len(times) // 2]
        out[str(nb)] = {
            "solves_per_s": round(nb / dt, 2),
            "solve_s": round(dt, 4),
            "iters_max": int(np.max(res.iterations)),
            "all_converged": bool(res.all_converged),
        }
    return out


def bench_serving(n: int = 32, smoke: bool = False,
                  aot_dir: str = None):
    """Serving phase (amgx_tpu/serving/): a synthetic OPEN-LOOP load —
    arrivals follow a fixed schedule, independent of completions —
    against the continuous-batching solve service. Traffic shape: a
    hot tenant streaming same-pattern systems with per-request value
    perturbations (the hierarchy-cache + value-resetup steady state), a
    cold tenant submitting a second pattern, and a slice of
    impossible-deadline requests that must complete with
    DEADLINE_EXCEEDED rather than stall their bucket.

    Two service processes are simulated: a WARMUP service traces the
    buckets and exports them to the AOT store, then a fresh MEASURED
    service starts from that store — so `retraces_after_warmup` counts
    the python traces a restarted production service would pay (the
    acceptance gate is ZERO). Figures of merit: sustained solves/sec
    over the measured window, p50/p99 submit-to-complete latency, the
    cache-hit rate and the setup-routing proof (value-resetups vs full
    setups during the window)."""
    import tempfile
    from amgx_tpu.presets import SERVING_CG
    from amgx_tpu.serving import SolveService
    from amgx_tpu.telemetry import metrics as _tm
    from amgx_tpu.resilience.status import SolveStatus

    if smoke:
        n, n_requests, arrival_dt = 10, 14, 0.0
    else:
        n_requests, arrival_dt = 60, 0.002
    if aot_dir is None:
        aot_dir = tempfile.mkdtemp(prefix="amgx_serving_aot_")
    cfg = Config.from_string(
        SERVING_CG + f", serving_bucket_slots=4, serving_chunk_iters=4,"
        f" serving_aot_dir={aot_dir}")

    hot = amgx.gallery.poisson("7pt", n, n, n).init()
    cold = amgx.gallery.poisson("7pt", n + 2, n + 2, n + 2).init()
    rng = np.random.default_rng(11)

    def shifted(A, c):
        vals = np.asarray(A.values).copy()
        vals[np.asarray(A.diag_idx)] += c
        return A.with_values(vals)

    # request schedule: (matrix, rhs, tenant, deadline). ~1/5 of the
    # traffic is the cold pattern, every 7th hot request carries an
    # already-expired deadline
    sched = []
    for i in range(n_requests):
        if i % 5 == 4:
            sched.append((cold, rng.standard_normal(cold.num_rows),
                          "cold", None))
        else:
            A_i = shifted(hot, 0.1 * (i % 3))
            dl = 0.0 if i % 7 == 3 else None
            sched.append((A_i, rng.standard_normal(hot.num_rows),
                          "hot", dl))

    # warmup service: builds both buckets, traces, exports to the store
    warm = SolveService(cfg)
    for A_i, b_i, tn, _dl in (sched[0], sched[4]):  # one per pattern
        warm.submit(A_i, b_i, tenant=tn)
    warm.drain(timeout_s=600)

    # measured service: a "restarted process" starting from the store
    base = _tm.snapshot()
    svc = SolveService(cfg)
    tickets = []
    t_start = time.perf_counter()
    next_i = 0
    while next_i < len(sched) or not svc.idle:
        now = time.perf_counter() - t_start
        while next_i < len(sched) and now >= next_i * arrival_dt:
            A_i, b_i, tn, dl = sched[next_i]
            tickets.append(svc.submit(A_i, b_i, tenant=tn,
                                      deadline_s=dl))
            next_i += 1
        svc.step()
        if time.perf_counter() - t_start > 600:   # pragma: no cover
            break
    window_s = time.perf_counter() - t_start

    cur = _tm.snapshot()

    def delta(name):
        return int(cur.get(name, 0) - base.get(name, 0))

    lat_ms = sorted(1e3 * t.latency_s for t in tickets if t.done
                    and t.deadline_t is None)
    n_solved = len(lat_ms)
    dl_tickets = [t for t in tickets if t.deadline_t is not None]
    dl_ok = all(
        t.done and t.result.status_code
        == int(SolveStatus.DEADLINE_EXCEEDED) for t in dl_tickets)
    hits, misses = delta("serving.cache.hit"), delta("serving.cache.miss")
    out = {
        "grid": f"{n}^3 poisson7pt (+ {n + 2}^3 cold pattern)",
        "requests": len(tickets),
        "window_s": round(window_s, 3),
        "solves_per_s": round(n_solved / max(window_s, 1e-9), 2),
        "p50_ms": round(lat_ms[len(lat_ms) // 2], 2) if lat_ms else -1,
        "p99_ms": round(lat_ms[min(len(lat_ms) - 1,
                                   int(0.99 * len(lat_ms)))], 2)
        if lat_ms else -1,
        "cache_hit_rate": round(hits / max(hits + misses, 1), 3),
        "value_resetups_routed": delta("amg.resetup.value"),
        "full_setups": delta("amg.setup.full"),
        "retraces_after_warmup": delta("serving.retrace"),
        "aot_loads": delta("serving.aot.load"),
        "deadline_requests": len(dl_tickets),
        "deadline_miss": delta("serving.deadline_miss"),
        "deadline_statuses_ok": bool(dl_ok),
        "all_completed": bool(all(t.done for t in tickets)),
        "smoke": bool(smoke),
    }
    return out


def bench_autotune(n: int = 16, smoke: bool = False):
    """Autotune phase (amgx_tpu/serving/autotune.py): the online
    per-fingerprint config tuner, measured on both sides of its
    contract.

    A: the WIN — a deliberately mistuned hot fingerprint (an
    overdamped BLOCK_JACOBI, the convergence-doctor classic) is served
    until hot, the tuner shadow-solves the diagnostics-derived
    candidates on idle cycles and promotes the winner; the SAME
    request set is then re-served under the promoted overlay. Figures
    of merit: median iterations and in-bucket wall before vs after
    (`autotune_speedup` = the smaller of the two ratios — the
    conservative claim; the gate is >= 2x on BOTH).

    B: the COST — the identical saturated burst runs against
    autotune=0 and autotune=1 services stepped in LOCKSTEP (one
    shared loop, so box noise lands on both arms' in-flight tickets
    identically; tuner eager: hot thresholds at the floor). Shadow
    solves only ever use idle capacity, so under saturation the tuner
    must be structurally inert: `autotune_shadow_p99_impact_pct` is
    the paired p99 delta (gate: within noise),
    `search_deadline_misses` the deadline misses the search added
    (gate: zero)."""
    import tempfile
    from amgx_tpu.presets import BATCHED_CG
    from amgx_tpu.serving import SolveService
    from amgx_tpu.telemetry import metrics as _tm

    if smoke:
        n, k_serve, k_pair = 8, 6, 10
    else:
        k_serve, k_pair = 12, 16
    root = tempfile.mkdtemp(prefix="amgx_autotune_")
    mistuned = (
        BATCHED_CG + ", amg:smoother(sm2)=BLOCK_JACOBI,"
        " sm2:max_iters=1, sm2:relaxation_factor=0.02,"
        " serving_bucket_slots=2, serving_chunk_iters=2")
    tuned_cfg = Config.from_string(
        mistuned + ", autotune=1, autotune_hot_requests=4,"
        " autotune_hot_exec_share=0.0,"
        f" serving_hierarchy_dir={root}/hier,"
        f" serving_journal_dir={root}/journal")

    A = amgx.gallery.poisson("7pt", n, n, n).init()
    rng = np.random.default_rng(7)
    rhs = [rng.standard_normal(A.num_rows) for _ in range(k_serve)]

    def exec_wall(t):
        return t.complete_t - t.admit_t

    def serve(svc, excl_first=1):
        tix = [svc.submit(A, b) for b in rhs]
        svc.drain(timeout_s=600)
        meas = tix[excl_first:]     # first request pays build+trace
        iters = sorted(t.result.iterations for t in meas)
        # iterations: median (exact, noise-free). wall: min — the
        # deterministic-cost estimator (OS scheduler jitter only ever
        # inflates a request's wall, identically on both sides)
        walls = sorted(exec_wall(t) for t in meas)
        return (tix, iters[len(iters) // 2], walls[0])

    # -- A: the win -------------------------------------------------------
    base = _tm.snapshot()
    svc = SolveService(tuned_cfg)
    tix, pre_iters, pre_wall = serve(svc)
    assert all(t.result.converged for t in tix)
    # idle cycles: baseline probe + candidate shadows + the verdict
    for _ in range(20):
        svc.step()
        if svc.stats()["autotune"]["promoted"]:
            break
    snap = svc.stats()["autotune"]
    tix2, post_iters, post_wall = serve(svc)
    cur = _tm.snapshot()

    def delta(name):
        return int(cur.get(name, 0) - base.get(name, 0))

    sp_iters = pre_iters / max(post_iters, 1)
    sp_wall = pre_wall / max(post_wall, 1e-9)
    rec = (next(iter(snap["fingerprints"].values()))
           if snap["fingerprints"] else {})

    # -- B: the cost (lockstep paired saturated open loop) ----------------
    # Both arms step in ONE shared loop: every scheduler stall,
    # neighbor steal, and allocator hiccup lands on BOTH arms'
    # in-flight tickets, so the paired p99 delta isolates what the
    # tuner itself adds (back-to-back arm runs drown a percent-level
    # delta in several percent of box noise). A service is stepped
    # only while it has traffic, so the on-arm's post-burst idle-time
    # shadows never spend the shared loop's clock inside the measured
    # window — and mid-burst shadows are exactly what the capacity
    # gate forbids (counted below, must be zero).
    off_cfg = mistuned + ", autotune=0"
    # warm-up stays below the hot threshold (4), so the on-arm tuner
    # goes hot on its FIRST burst finish: hot-path bookkeeping and
    # shadow gating are live for the whole measured burst
    on_cfg = (mistuned + ", autotune=1, autotune_hot_requests=4,"
              " autotune_hot_exec_share=0.0")
    svcs = [SolveService(Config.from_string(c))
            for c in (off_cfg, on_cfg)]
    prng = np.random.default_rng(13)
    warm = [prng.standard_normal(A.num_rows) for _ in range(3)]
    for svc in svcs:
        for b in warm:
            svc.submit(A, b)
        svc.drain(timeout_s=600)
    d0 = _tm.get("serving.deadline_miss")
    r0 = _tm.get("autotune.shadow.runs")
    sched = [prng.standard_normal(A.num_rows) for _ in range(k_pair)]
    c0 = time.process_time()
    pair_tix = [[svc.submit(A, b) for b in sched] for svc in svcs]
    t0 = time.perf_counter()
    runs_during = 0
    while any(not svc.idle for svc in svcs):
        for svc in svcs:
            if not svc.idle:
                svc.step()
        if any(not t.done for tt in pair_tix for t in tt):
            # traffic still in flight: any shadow counted so far ran
            # CONCURRENTLY with production — the structural violation
            # the capacity gate exists to prevent. (Shadows in the
            # drained tail are the tuner doing its job.)
            runs_during = _tm.get("autotune.shadow.runs") - r0
        if time.perf_counter() - t0 > 600:  # pragma: no cover
            break

    def p99_ms(tickets, stamp):
        lat = sorted(stamp(t) for t in tickets if t.done)
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    def wall(t):
        return 1e3 * t.latency_s

    def cpu(t):
        # the process-CPU completion stamp: the ruler neighbor steal
        # cannot touch (a mid-burst shadow would burn process CPU and
        # shift every later completion)
        return 1e3 * (t.complete_cpu_t - c0)

    p99_off = p99_ms(pair_tix[0], wall)
    p99_on = p99_ms(pair_tix[1], wall)
    impact_cpu_pct = 100.0 * (
        p99_ms(pair_tix[1], cpu) - p99_ms(pair_tix[0], cpu)) \
        / max(p99_ms(pair_tix[0], cpu), 1e-9)
    miss_on = _tm.get("serving.deadline_miss") - d0
    miss_off = 0
    runs_on = int(runs_during)
    impact_pct = 100.0 * (p99_on - p99_off) / max(p99_off, 1e-9)

    return {
        "grid": f"{n}^3 poisson7pt",
        "mistuning": "BLOCK_JACOBI relaxation_factor=0.02",
        "promoted_knob": rec.get("knob"),
        "promoted_overlay": rec.get("overlay"),
        "shadow_runs": delta("autotune.shadow.runs"),
        "shadow_errors": delta("autotune.shadow.errors"),
        "promotions": delta("autotune.promotions"),
        "pre_iters_median": int(pre_iters),
        "post_iters_median": int(post_iters),
        "pre_exec_wall_ms": round(1e3 * pre_wall, 2),
        "post_exec_wall_ms": round(1e3 * post_wall, 2),
        "autotune_speedup_iters": round(sp_iters, 3),
        "autotune_speedup_wall": round(sp_wall, 3),
        "autotune_speedup": round(min(sp_iters, sp_wall), 3),
        "search_deadline_misses": delta("serving.deadline_miss"),
        "paired_requests": k_pair,
        "paired_design": "lockstep",
        "paired_p99_off_ms": round(p99_off, 2),
        "paired_p99_on_ms": round(p99_on, 2),
        "autotune_shadow_p99_cpu_impact_pct": round(impact_cpu_pct, 2),
        "autotune_shadow_p99_impact_pct": round(impact_pct, 2),
        "paired_deadline_misses": int(miss_on - miss_off),
        "saturated_shadow_runs": int(runs_on),
        "all_completed": bool(all(t.done for t in tix + tix2)),
        "smoke": bool(smoke),
    }


def bench_fleet(n: int = 16, smoke: bool = False):
    """Fleet phase (amgx_tpu/serving/fleet.py): the fingerprint-affine
    replica router vs ONE replica of the identical per-replica config,
    under a load built to expose the placement lever the router
    actually owns — which hierarchy stays warm where. Three sections:

    1. SCALING — a wave-interleaved load alternates two hot sparsity
       patterns, each wave value-perturbed same-pattern systems, with a
       drain boundary between waves. Per-replica
       `serving_cache_entries=1`: the single replica evicts the idle
       bucket at every pattern switch and pays a full hierarchy setup
       per wave, while the 2-replica fleet's rendezvous affinity pins
       each pattern to its home replica so every wave after the first
       sighting rides the value-resetup path. Both runs see the
       IDENTICAL schedule (waves 0+1 land together so the router's
       least-loaded cold placement observes real queue imbalance —
       and the single service gets the same burst). The headline is
       sustained solves/sec fleet vs single and the per-replica route
       counters proving >= 90% affine service.

       HONEST FRAMING: on this rig every replica shares one CPU core
       and one jax device, so the fleet CANNOT win on parallel
       compute — the measured scaling is the aggregate-cache-capacity
       + affinity effect (the fleet's combined cache holds the whole
       working set; the single replica's cannot), which is exactly the
       lever the router exists to exercise. It can exceed 2x for the
       same reason a working set crossing a cache boundary does.
       Compute scaling needs multi-host replicas.

    2. AFFINITY under saturation rides section 1's route counters:
       spills require a strictly-less-loaded candidate, so uniform
       overload keeps traffic home instead of ping-ponging cold
       builds.

    3. SHED AT 2x SATURATION — the bench_chaos section-3 pattern
       against the fleet: train both replicas' latency estimators,
       measure the fleet's closed-loop service rate, then drive
       open-loop arrivals at 2x that rate (on this one-core rig the
       fleet's closed-loop rate on warm alternating traffic is at
       least the single replica's, so this overdrives 2x
       single-replica saturation) with a deadline a few multiples of
       the per-request service time. Gates: every shed classified
       OVERLOADED (the fleet-wide feasibility consult routes the
       request home for an honest per-replica shed, never a silent
       drop), ZERO admitted request finishing DEADLINE_EXCEEDED, and
       admitted p99 within the deadline budget."""
    from amgx_tpu.presets import SERVING_CG
    from amgx_tpu.serving import FleetRouter, SolveService
    from amgx_tpu.telemetry import metrics as _tm
    from amgx_tpu.resilience.status import SolveStatus

    if smoke:
        n, waves, per_wave, slots = 10, 4, 2, 2
    else:
        waves, per_wave, slots = 8, 4, 4
    base_cfg = (SERVING_CG + f", serving_bucket_slots={slots},"
                f" serving_chunk_iters=4, serving_cache_entries=1")
    cfg = Config.from_string(base_cfg)

    pat_a = amgx.gallery.poisson("7pt", n, n, n).init()
    pat_b = amgx.gallery.poisson("7pt", n + 1, n + 1, n + 1).init()
    rng = np.random.default_rng(23)

    def shifted(A, c):
        vals = np.asarray(A.values).copy()
        vals[np.asarray(A.diag_idx)] += c
        return A.with_values(vals)

    # one schedule, built once, replayed verbatim against both systems
    sched, ctr = [], 0
    for w in range(waves):
        A = pat_a if w % 2 == 0 else pat_b
        wave = []
        for _j in range(per_wave):
            wave.append((shifted(A, 0.1 * (ctr % 3)),
                         rng.standard_normal(A.num_rows)))
            ctr += 1
        sched.append(wave)

    # pre-warm a throwaway service on both patterns so process-global
    # compile caches are equally hot for both measured runs (the later
    # run must not inherit a warmup the earlier one paid for)
    warm = SolveService(Config.from_string(
        base_cfg.replace("serving_cache_entries=1",
                         "serving_cache_entries=2")))
    warm.submit(*sched[0][0])
    warm.submit(*sched[1][0])
    warm.drain(timeout_s=600)
    del warm

    def run_sched(submit, drain):
        """Replay the wave schedule closed-loop: waves 0+1 land
        together (cold placement sees real load), then a drain
        boundary per wave — the boundary idles every bucket, which is
        what lets the one-entry cache evict on the next pattern's
        build."""
        tickets = []
        t0 = time.perf_counter()
        for w, wave in enumerate(sched):
            for A_i, b_i in wave:
                tickets.append(submit(A_i, b_i))
            if w != 0:
                drain()
        return tickets, time.perf_counter() - t0

    def delta(cur, base, name):
        return int(cur.get(name, 0) - base.get(name, 0))

    # -- 1a. single-replica baseline (identical per-replica config) ------
    base = _tm.snapshot()
    svc = SolveService(cfg)
    ts_single, wall_single = run_sched(
        svc.submit, lambda: svc.drain(timeout_s=600))
    cur = _tm.snapshot()
    single_setups = delta(cur, base, "amg.setup.full")
    single_evicts = delta(cur, base, "serving.cache.evictions")
    single_ok = all(t.done and t.result.converged for t in ts_single)

    # -- 1b. the 2-replica fleet, same schedule --------------------------
    base = _tm.snapshot()
    fleet = FleetRouter.build(cfg, n_replicas=2)
    ts_fleet, wall_fleet = run_sched(
        fleet.submit, lambda: fleet.drain(timeout_s=600))
    cur = _tm.snapshot()
    fleet_setups = delta(cur, base, "amg.setup.full")
    fleet_resetups = delta(cur, base, "amg.resetup.value")
    fleet_done_ok = all(t.done and t.result.converged for t in ts_fleet)

    routes = fleet.stats()["routes"]
    n_warm = sum(c["warm"] for c in routes.values())
    n_cold = sum(c["cold"] for c in routes.values())
    n_spill = sum(c["spill"] for c in routes.values())
    # affinity: of every request with an established home (all but the
    # cold first-sightings), the fraction its affine replica served
    affinity_rate = n_warm / max(n_warm + n_spill, 1)

    n_req = len(ts_single)
    single_sps = n_req / max(wall_single, 1e-9)
    fleet_sps = n_req / max(wall_fleet, 1e-9)
    scaling_x = fleet_sps / max(single_sps, 1e-9)

    # -- 3. shed accuracy at 2x saturation -------------------------------
    fleet2 = FleetRouter.build(
        Config.from_string(base_cfg + ", serving_shed_policy=deadline"),
        n_replicas=2)
    pats = (pat_a, pat_b)

    def sat_req(i):
        A = pats[i % 2]
        return shifted(A, 0.1 * (i % 3)), rng.standard_normal(A.num_rows)

    for i in range(8):                    # train both estimators
        fleet2.submit(*sat_req(i))
    fleet2.drain(timeout_s=600)
    k = 8 if smoke else 24
    t0 = time.perf_counter()
    closed = [fleet2.submit(*sat_req(i)) for i in range(k)]
    fleet2.drain(timeout_s=600)
    assert all(t.done for t in closed)
    per_req = (time.perf_counter() - t0) / k
    # deadline budget in the admission estimator's own unit: 4x the
    # worst idle-replica feasibility estimate (single-request
    # residence + safety margins), floored by the chaos-phase rule of
    # a few multiples of the closed-loop per-request rate — so an
    # idle fleet ADMITS, a 2x-overdriven backlog turns infeasible and
    # SHEDS, and the gap between the shed threshold (estimate crosses
    # the deadline) and the deadline itself absorbs the estimator's
    # contention error on admitted work near the threshold
    est_idle = max((fleet2.replicas[r]._estimate_latency_s() or 0.0)
                   for r in fleet2.replicas)
    deadline_s = max(4 * est_idle, 8 * per_req, 0.05)
    arrival_dt = per_req / 2.0            # 2x the fleet's service rate
    n_sat = 24 if smoke else 48
    import gc
    gc.collect()          # no mid-burst GC pause from prior sections
    base = _tm.snapshot()
    tickets = []
    t0 = time.perf_counter()
    next_i = 0
    while next_i < n_sat or not fleet2.idle:
        now = time.perf_counter() - t0
        while next_i < n_sat and now >= next_i * arrival_dt:
            A_i, b_i = sat_req(next_i)
            tickets.append(fleet2.submit(A_i, b_i,
                                         deadline_s=deadline_s))
            next_i += 1
        fleet2.step()
        if time.perf_counter() - t0 > 600:   # pragma: no cover
            break
    fleet2.drain(timeout_s=600)
    cur = _tm.snapshot()
    shed = [t for t in tickets if t.done and t.result.status_code
            == int(SolveStatus.OVERLOADED)]
    shed_ids = {id(t) for t in shed}
    admitted = [t for t in tickets if id(t) not in shed_ids]
    adm_miss = [t for t in admitted if t.done and t.result.status_code
                == int(SolveStatus.DEADLINE_EXCEEDED)]
    lat = sorted(1e3 * t.latency_s for t in admitted if t.done)
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))] if lat else -1.0
    sat_ok = bool(all(t.done for t in tickets) and not adm_miss
                  and all(t.result.status == "overloaded" for t in shed)
                  and (p99 < 0 or p99 <= 1e3 * deadline_s))

    # -- 4. failover: kill 1 of 2 mid-load ------------------------------
    # The fleet-level kill-and-recover drill (bench_chaos section 1
    # raised to the router): a journaled 2-replica fleet takes a mixed
    # two-pattern load, steps until the first ticket's home replica
    # holds admitted + checkpointed work, then that replica is killed
    # (chaos replica_kill). Gates: ZERO lost tickets (every submit
    # terminal), the moved solves finish BIT-IDENTICAL to an
    # uninterrupted twin fleet, the victim reads DOWN, and at least
    # one ticket actually changed replicas. fleet_failover_wall_s is
    # kill -> last victim-homed ticket terminal.
    import shutil
    import tempfile
    from amgx_tpu.resilience import faultinject
    k_fo = 6 if smoke else 12
    reqs = [sat_req(1000 + i) for i in range(k_fo)]
    fo_dirs = [tempfile.mkdtemp(prefix="amgx_fleet_fo_")
               for _ in range(2)]
    fo_base = (base_cfg + ", serving_chunk_iters=1,"
               " serving_checkpoint_cycles=1")
    ref_fleet = FleetRouter.build(Config.from_string(
        fo_base + f", serving_journal_dir={fo_dirs[0]}"), n_replicas=2)
    ref_ts = [ref_fleet.submit(A_i, b_i) for A_i, b_i in reqs]
    ref_fleet.drain(timeout_s=600)
    xrefs = [np.asarray(t.result.x) for t in ref_ts]
    flt = FleetRouter.build(Config.from_string(
        fo_base + f", serving_journal_dir={fo_dirs[1]}"), n_replicas=2)
    fo_ts = [flt.submit(A_i, b_i) for A_i, b_i in reqs]
    victim = fo_ts[0].replica
    orig_replica = [t.replica for t in fo_ts]
    for _ in range(3):     # admit + checkpoint work on the victim
        flt.step()
    t0 = time.monotonic()
    with faultinject.inject("replica_kill", fires=1, target=victim):
        flt.drain(timeout_s=600)
    fo_lost = sum(0 if t.done else 1 for t in fo_ts)
    vt = [t for t, r0 in zip(fo_ts, orig_replica)
          if r0 == victim and t.done]
    failover_wall = (max(t.complete_t for t in vt) - t0) if vt else -1.0
    fo_bit_same = bool(all(
        t.done and np.array_equal(np.asarray(t.result.x), xr)
        for t, xr in zip(fo_ts, xrefs)))
    fo_moved = sum(1 for t, r0 in zip(fo_ts, orig_replica)
                   if t.replica != r0)
    fo_down = bool(flt.health_snapshot()[victim]["down"])
    failover_ok = bool(fo_lost == 0 and fo_bit_same and fo_moved > 0
                       and fo_down
                       and all(t.done and t.result.converged
                               for t in fo_ts))
    for d in fo_dirs:
        shutil.rmtree(d, ignore_errors=True)

    scaling_ok = bool(scaling_x >= 1.7)
    affinity_ok = bool(affinity_rate >= 0.90)
    out = {
        "grid": f"{n}^3 + {n + 1}^3 poisson7pt, {waves} waves x "
                f"{per_wave}, bucket_slots={slots}, cache_entries=1",
        "requests_per_run": n_req,
        "single_solves_per_s": round(single_sps, 2),
        "fleet_solves_per_s": round(fleet_sps, 2),
        "fleet_scaling_x": round(scaling_x, 3),
        "fleet_scaling_efficiency": round(scaling_x / 2.0, 3),
        "fleet_n_replicas": 2,
        "single_full_setups": single_setups,
        "single_cache_evictions": single_evicts,
        "fleet_full_setups": fleet_setups,
        "fleet_value_resetups": fleet_resetups,
        "fleet_affinity_rate": round(affinity_rate, 4),
        "routes": {rid: dict(c) for rid, c in routes.items()},
        "route_warm": n_warm, "route_cold": n_cold,
        "route_spill": n_spill,
        "all_completed": bool(single_ok and fleet_done_ok),
        "sat_deadline_ms": round(1e3 * deadline_s, 2),
        "sat_requests": len(tickets),
        "sat_shed_rate": round(len(shed) / max(len(tickets), 1), 3),
        "sat_admitted_deadline_misses": len(adm_miss),
        "fleet_p99_at_2x_ms": round(p99, 2),
        "fleet_shed_consults": delta(cur, base, "fleet.shed.infeasible"),
        "sat_ok": sat_ok,
        "failover_requests": k_fo,
        "failover_victim": victim,
        "failover_moved_tickets": fo_moved,
        "failover_bit_identical": fo_bit_same,
        "fleet_failover_wall_s": round(failover_wall, 4),
        "fleet_failover_lost_requests": int(fo_lost),
        "failover_ok": failover_ok,
        "scaling_ok": scaling_ok,
        "affinity_ok": affinity_ok,
        "fleet_ok": bool(scaling_ok and affinity_ok and sat_ok
                         and failover_ok
                         and single_ok and fleet_done_ok),
        "smoke": bool(smoke),
    }
    return out


def bench_chaos(n: int = 16, smoke: bool = False):
    """Chaos phase (serving fault tolerance, amgx_tpu/serving/ +
    resilience/faultinject.py service kinds). Three measurements:

    1. KILL-AND-RECOVER — a journaled + hierarchy-persisted + AOT'd
       service is killed mid-flight; its successor replays the journal
       and must (a) resume the checkpointed solves to final iterates
       BIT-IDENTICAL to an uninterrupted run, (b) pay ZERO full AMG
       setups (persisted structures) and ZERO engine retraces (AOT) —
       `chaos_recover_wall_s` is the successor's construct-to-drained
       wall, the restart-story headline.
    2. SCRIPTED FAULT SCENARIOS — builder crash (with retry_backoff
       recovery), device-step exception (quarantine + requeue), wedged
       bucket (heartbeat supervisor), journal corruption (torn write
       dropped at replay), AOT-store corruption (degrades to
       retracing), clock-skewed deadlines. Gate: every scenario ends
       with 100% of tickets terminal — no hangs, no lost requests.
    3. SHED ACCURACY AT 2x SATURATION — open-loop arrivals at twice
       the measured closed-loop service rate with per-request
       deadlines and `serving_shed_policy=deadline`. Gates: sheds are
       classified OVERLOADED, no ADMITTED request ends
       DEADLINE_EXCEEDED, and the accepted p99 stays within the
       deadline budget (`chaos_accepted_p99_ms`)."""
    import shutil
    import tempfile
    from amgx_tpu.presets import SERVING_CG
    from amgx_tpu.resilience import faultinject as fi
    from amgx_tpu.resilience.status import SolveStatus
    from amgx_tpu.serving import SolveService
    from amgx_tpu.telemetry import flightrec as _frec
    from amgx_tpu.telemetry import metrics as _tm

    if smoke:
        n = 10
    root = tempfile.mkdtemp(prefix="amgx_chaos_")
    dirs = (f"serving_aot_dir={root}/aot,"
            f" serving_hierarchy_dir={root}/hier,"
            f" serving_journal_dir={root}/journal")
    base_cfg = (SERVING_CG + ", serving_bucket_slots=4,"
                " serving_chunk_iters=2")
    A = amgx.gallery.poisson("7pt", n, n, n).init()
    rng = np.random.default_rng(7)
    bs = [rng.standard_normal(A.num_rows) for _ in range(6)]
    out = {"grid": f"{n}^3 poisson7pt", "smoke": bool(smoke)}

    def svc_new(extra=""):
        return SolveService(Config.from_string(
            base_cfg + (", " + extra if extra else "")))

    # -- 1. kill-and-recover ---------------------------------------------
    # tight tolerance + 1-iteration chunks so the kill lands
    # mid-flight (the tiny grid would otherwise finish before it)
    kr = "s:tolerance=1e-12, serving_chunk_iters=1"
    ref = svc_new(kr)
    refs = [ref.submit(A, b) for b in bs[:3]]
    ref.drain(timeout_s=600)
    jcfg = dirs + ", serving_checkpoint_cycles=1, " + kr
    victim = svc_new(jcfg)
    vt = [victim.submit(A, b, request_key=f"kr-{i}")
          for i, b in enumerate(bs[:3])]
    for _ in range(3):          # build + a couple of cycles, then die
        victim.step()
    out["killed_inflight"] = sum(not t.done for t in vt)
    del victim
    base = _tm.snapshot()
    t0 = time.perf_counter()
    succ = svc_new(jcfg)        # journal replays at construction
    done = succ.drain(timeout_s=600)
    recover_wall = time.perf_counter() - t0
    cur = _tm.snapshot()

    def delta(name):
        return int(cur.get(name, 0) - base.get(name, 0))

    by_key = {t.request_key: t for t in done if t.request_key}
    bitwise = bool(by_key) \
        and delta("serving.recovery.resumed") > 0 and all(
        t.done and np.array_equal(np.asarray(t.result.x),
                                  np.asarray(refs[int(k.split("-")[1])]
                                             .result.x))
        for k, t in by_key.items())
    out.update({
        "chaos_recover_wall_s": round(recover_wall, 3),
        "recover_replayed": delta("serving.recovery.replayed"),
        "recover_resumed": delta("serving.recovery.resumed"),
        "recover_bitwise_ok": bitwise,
        "restart_full_setups": delta("amg.setup.full"),
        "restart_hier_restored": delta("amg.setup.restored"),
        "restart_retraces": delta("serving.retrace"),
        "recover_all_terminal": bool(all(t.done for t in done)
                                     and succ.idle),
    })

    # -- 2. scripted fault scenarios -------------------------------------
    scen_ok = {}

    def terminal(tickets, svc):
        return bool(all(t.done for t in tickets) and svc.idle)

    def fr_cause(kind, since):
        """The flight-recorder postmortem contract per scenario: the
        LAST chaos event recorded since the scenario started names
        the injected fault — the event trail explains what hit the
        service, not merely that something did."""
        chaos = _frec.events(kind="chaos", since_seq=since)
        return bool(chaos) and chaos[-1].get("fault") == kind

    # builder crash -> bounded backoff retry -> converges
    seq0 = _frec.last_seq()
    svc = svc_new("serving_fault_policy=BUILD_FAILED>retry_backoff,"
                  " serving_retry_backoff_s=0.01")
    with fi.inject("build_crash", fires=1):
        ts = [svc.submit(A, bs[0])]
        svc.drain(timeout_s=600)
    scen_ok["builder_crash"] = terminal(ts, svc) and \
        ts[0].result.converged and fr_cause("build_crash", seq0) and \
        bool(_frec.events(kind="bucket.build_failed", since_seq=seq0))
    # device-step exception -> quarantine -> requeue -> rebuilt bucket
    seq0 = _frec.last_seq()
    svc = svc_new()
    ts = [svc.submit(A, b) for b in bs[:2]]
    svc.step()
    with fi.inject("step_crash", fires=1):
        svc.step()
    svc.drain(timeout_s=600)
    scen_ok["step_crash"] = terminal(ts, svc) and \
        all(t.result.converged for t in ts) and \
        fr_cause("step_crash", seq0) and \
        bool(_frec.events(kind="bucket.quarantine", since_seq=seq0))
    # wedged bucket -> heartbeat supervisor quarantine
    seq0 = _frec.last_seq()
    svc = svc_new("serving_supervisor_cycles=2")
    ts = [svc.submit(A, bs[0])]
    svc.step()
    with fi.inject("step_wedge", fires=6):
        for _ in range(6):
            svc.step()
    svc.drain(timeout_s=600)
    scen_ok["wedged_bucket"] = terminal(ts, svc) and \
        fr_cause("step_wedge", seq0)
    # journal torn write -> dropped at replay, successor keeps serving
    seq0 = _frec.last_seq()
    jd2 = tempfile.mkdtemp(prefix="amgx_chaos_j2_")
    svc = svc_new(f"serving_journal_dir={jd2}")
    with fi.inject("journal_corrupt", fires=1):
        svc.submit(A, bs[0])
    del svc
    svc = svc_new(f"serving_journal_dir={jd2}")
    ts = [svc.submit(A, bs[1])]
    svc.drain(timeout_s=600)
    scen_ok["journal_corrupt"] = terminal(ts, svc) and \
        ts[0].result.converged and fr_cause("journal_corrupt", seq0)
    # AOT-store torn write -> load fails -> degrades to retracing
    seq0 = _frec.last_seq()
    ad2 = tempfile.mkdtemp(prefix="amgx_chaos_a2_")
    with fi.inject("aot_corrupt", fires=None):
        svc = svc_new(f"serving_aot_dir={ad2}")
        svc.submit(A, bs[0])
        svc.drain(timeout_s=600)
    scen_aot_cause = fr_cause("aot_corrupt", seq0)
    svc = svc_new(f"serving_aot_dir={ad2}")
    ts = [svc.submit(A, bs[1])]
    svc.drain(timeout_s=600)
    scen_ok["aot_corrupt"] = terminal(ts, svc) and \
        ts[0].result.converged and scen_aot_cause
    # clock skew: deadline bookkeeping under a shifted clock
    seq0 = _frec.last_seq()
    with fi.inject("clock_skew", value=300.0, fires=None):
        svc = svc_new()
        ts = [svc.submit(A, bs[0], deadline_s=1e9),
              svc.submit(A, bs[1])]
        svc.drain(timeout_s=600)
    scen_ok["clock_skew"] = terminal(ts, svc) and \
        fr_cause("clock_skew", seq0)
    out["chaos_scenarios"] = scen_ok
    out["chaos_all_terminal"] = bool(all(scen_ok.values()))

    # -- 3. shedding at 2x saturation ------------------------------------
    svc = svc_new("serving_shed_policy=deadline")
    warm = [svc.submit(A, b) for b in bs[:4]]
    svc.drain(timeout_s=600)          # warm + train the exec histogram
    k = 8 if smoke else 24
    t0 = time.perf_counter()
    closed = [svc.submit(A, bs[i % len(bs)]) for i in range(k)]
    svc.drain(timeout_s=600)
    assert all(t.done for t in closed)
    per_req = (time.perf_counter() - t0) / k   # closed-loop service rate
    # deadline budget: a few multiples of the measured closed-loop
    # per-request service time (about 2 execution waves at this bucket
    # width), floored for rig noise — tight enough that a 2x-overdriven
    # queue makes tail requests genuinely unmeetable, so the shed
    # policy has real work to do
    deadline_s = max(8 * per_req, 0.05)
    arrival_dt = per_req / 2.0                 # 2x saturation arrivals
    n_req = 24 if smoke else 48
    tickets = []
    t0 = time.perf_counter()
    next_i = 0
    while next_i < n_req or not svc.idle:
        now = time.perf_counter() - t0
        while next_i < n_req and now >= next_i * arrival_dt:
            tickets.append(svc.submit(A, bs[next_i % len(bs)],
                                      deadline_s=deadline_s))
            next_i += 1
        svc.step()
        if time.perf_counter() - t0 > 600:   # pragma: no cover
            break
    svc.drain(timeout_s=600)
    shed = [t for t in tickets if t.done and t.result.status_code
            == int(SolveStatus.OVERLOADED)]
    shed_ids = {id(t) for t in shed}
    admitted = [t for t in tickets if id(t) not in shed_ids]
    adm_miss = [t for t in admitted if t.done and t.result.status_code
                == int(SolveStatus.DEADLINE_EXCEEDED)]
    lat = sorted(1e3 * t.latency_s for t in admitted if t.done)
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))] if lat else -1.0
    out.update({
        "shed_deadline_ms": round(1e3 * deadline_s, 2),
        "shed_rate": round(len(shed) / max(len(tickets), 1), 3),
        "chaos_accepted_p99_ms": round(p99, 2),
        "shed_admitted_deadline_misses": len(adm_miss),
        "shed_all_overloaded": bool(all(
            t.result.status == "overloaded" for t in shed)),
        "shed_ok": bool(all(t.done for t in tickets)
                        and not adm_miss
                        and (p99 < 0 or p99 <= 1e3 * deadline_s)),
    })
    shutil.rmtree(root, ignore_errors=True)
    return out


def bench_resilience(n: int = 32, iters: int = 300, reps: int = 9):
    """Resilience smoke phase: per-iteration cost of the guarded solve
    loop (health_guards=1, the default: NaN/breakdown/divergence
    classification riding the residual check) vs the unguarded loop
    (health_guards=0, the pre-resilience monitor). Both run CG to a
    full `iters` iterations (unreachable tolerance) on the n^3 7-pt
    Poisson so the quotient isolates the in-loop guard cost; the
    acceptance gate is overhead_pct <= 2. (The opt-in stall window is
    excluded: CG's early L2 residual is non-monotone, so a window
    would legitimately end the guarded run early and skew the
    per-iteration quotient.)"""
    from amgx_tpu.resilience.status import SolveStatus
    A = amgx.gallery.poisson("7pt", n, n, n).init()
    b = jnp.ones(A.num_rows)
    solvers = {}
    for tag, extra in (
            ("guarded", "health_guards=1"),
            ("unguarded", "health_guards=0")):
        cfg = Config.from_string(
            f"solver=CG, max_iters={iters}, monitor_residual=1,"
            f" tolerance=1e-30, convergence=RELATIVE_INI, {extra}")
        slv = amgx.create_solver(cfg)
        slv.setup(A)
        slv.solve(b)                           # compile
        solvers[tag] = slv
    # rig noise swings single measurements several percent either way;
    # pair each guarded sample with an adjacent unguarded one and take
    # the MEDIAN per-pair ratio (the bench_spmv_vs_ceiling technique)
    out = {}
    ratios, best = [], {"guarded": float("inf"),
                        "unguarded": float("inf")}
    for _ in range(2 * reps + 1):
        pair = {}
        for tag in ("guarded", "unguarded"):
            t0 = time.perf_counter()
            res = solvers[tag].solve(b)
            pair[tag] = time.perf_counter() - t0
            best[tag] = min(best[tag], pair[tag])
            out[tag] = {
                "per_iter_us": round(
                    best[tag] / max(res.iterations, 1) * 1e6, 2),
                "iters": int(res.iterations),
                "status": res.status,
            }
        ratios.append(pair["guarded"] / pair["unguarded"])
    ratios.sort()
    # headline: MEDIAN per-pair ratio (paired quotients cancel the
    # scheduler noise both sides share; the min-of-N ratio proved
    # jumpier on shared rigs); best-of mins and the pair spread are
    # kept to show the noise floor the headline was pulled from
    out["overhead_pct"] = round(
        100.0 * (ratios[len(ratios) // 2] - 1.0), 2)
    out["overhead_pct_bestof"] = round(
        100.0 * (best["guarded"] / best["unguarded"] - 1.0), 2)
    out["overhead_pct_pair_spread"] = [
        round(100.0 * (ratios[0] - 1.0), 2),
        round(100.0 * (ratios[-1] - 1.0), 2)]
    # prove the guards actually fire on this rig, not just cost little:
    # one NaN-injected solve must exit early with NAN_DETECTED
    from amgx_tpu.resilience import faultinject as _fi
    slv = amgx.create_solver(Config.from_string(
        f"solver=CG, max_iters={iters}, monitor_residual=1,"
        f" tolerance=1e-30, convergence=RELATIVE_INI"))
    slv.setup(A)
    with _fi.inject("spmv_nan", iteration=3):
        res = slv.solve(b)
    out["nan_inject_status"] = res.status
    out["nan_inject_detected_at"] = int(res.iterations)
    out["guards_fire"] = bool(
        res.status_code == SolveStatus.NAN_DETECTED)
    return out


def _classical_cfg(smoother: str = "JACOBI_L1", extra: str = ""):
    """The benched classical configuration (bench_classical's literal),
    shared with the obs phase so both replay the SAME config. The
    128^3 TPU line requests MULTICOLOR_DILU (the reference's classical
    smoother) and rides the PR-11 known-fault guard: above 96^3 on a
    single TPU chip it falls back to JACOBI_L1 with a warning and a
    `resilience.config_fallback` count — recorded in the bench line so
    the fallback is visible, not silent — and the fallback smoother
    takes the fused classical path (weighted transfer slabs +
    single-pass smoother kernels on the DIA fine level)."""
    return Config.from_string(
        "config_version=2, solver(s)=PCG, s:max_iters=100,"
        " s:tolerance=1e-8, s:convergence=RELATIVE_INI,"
        " s:monitor_residual=1, s:preconditioner(amg)=AMG,"
        " amg:algorithm=CLASSICAL, amg:selector=PMIS,"
        f" amg:interpolator=D2, amg:smoother={smoother},"
        " amg:presweeps=1,"
        " amg:postsweeps=1, amg:max_iters=1,"
        " amg:coarse_solver=DENSE_LU_SOLVER, amg:min_coarse_rows=32,"
        " amg:max_levels=20, amg:strength_threshold=0.25,"
        " amg:interp_max_elements=4, amg:max_row_sum=0.9,"
        " amg:amg_precision=float" + extra)


def bench_obs(n_flagship: int = 128, n_classical: int = 64,
              reps: int = 7):
    """Observability phase (`python bench.py obs`): replay the flagship
    and classical configs INSTRUMENTED and record what the telemetry
    subsystem says about them — the full structured SolveReport per
    config, the process-wide counter/gauge dump (structure-cache
    hit/miss, setup routing, retrace counts, memory watermarks), and a
    Perfetto trace-event export of the recorded host spans.

    Acceptance gates carried in the payload:
    - `overhead_pct`: paired-median per-iteration cost of the
      instrumented (telemetry=1) flagship solve vs telemetry=0 — must
      be within rig noise (the report is built host-side from the
      stats array the solve already returns; the traced program is
      identical by construction, so this measures ~0 plus noise);
    - `*_report_valid`: each emitted report validates against the
      checked-in schema (telemetry/report_schema.json);
    - `perfetto_valid`: the exported trace file loads as JSON.
    """
    import os

    from amgx_tpu.telemetry import metrics, spans, validate_report

    out = {}
    metrics.reset()

    # ---- flagship, instrumented vs uninstrumented ---------------------
    A = amgx.gallery.poisson("7pt", n_flagship, n_flagship,
                             n_flagship).init()
    b = jnp.ones(A.num_rows)
    slv_on = amgx.create_solver(Config.from_string(FLAGSHIP))
    slv_off = amgx.create_solver(Config.from_string(
        FLAGSHIP + ", telemetry=0"))
    slv_on.setup(A)
    slv_off.setup(A)
    res_on = slv_on.solve(b)          # compile
    res_off = slv_off.solve(b)
    assert res_off.report is None and res_on.report is not None
    # paired per-iteration quotients (the bench_resilience technique):
    # rig noise cancels in each pair, the median is the headline
    ratios = []
    for _ in range(reps):
        t0 = time.perf_counter()
        res_on = slv_on.solve(b)
        dt_on = time.perf_counter() - t0
        t0 = time.perf_counter()
        res_off = slv_off.solve(b)
        dt_off = time.perf_counter() - t0
        ratios.append((dt_on / max(res_on.iterations, 1))
                      / (dt_off / max(res_off.iterations, 1)))
    ratios.sort()
    out["overhead_pct"] = round(
        100.0 * (ratios[len(ratios) // 2] - 1.0), 2)
    out["overhead_pct_pair_spread"] = [
        round(100.0 * (ratios[0] - 1.0), 2),
        round(100.0 * (ratios[-1] - 1.0), 2)]
    out["overhead_ok"] = bool(abs(out["overhead_pct"]) <= 2.0)
    rep = res_on.report.to_dict()
    errs = validate_report(rep)
    out[f"flagship_{n_flagship}^3_report"] = rep
    out["flagship_report_valid"] = not errs
    if errs:
        out["flagship_report_schema_errors"] = errs[:10]
    # the warm-setup headline is now IN the standard report (the 256^3
    # warm-setup footnote check reads report.setup_time_s instead of
    # only the BENCH breakdown)
    out[f"flagship_{n_flagship}^3_report_setup_s"] = round(
        rep["setup_time_s"], 3)

    # ---- convergence diagnostics (diagnostics=1 probe) ----------------
    # the flagship replayed with the diagnostics knob: the report must
    # name a bottleneck level with per-level reduction factors — the
    # per-round proof that the probe works at the flagship's
    # REFINEMENT -> FGMRES -> AMG nesting depth on the real chip
    try:
        slv_d = amgx.create_solver(Config.from_string(
            FLAGSHIP + ", diagnostics=1"))
        slv_d.setup(A)
        res_d = slv_d.solve(b)
        dg = (res_d.report.diagnostics
              if res_d.report is not None else None)
        out["diagnostics"] = dg
        out["diagnostics_bottleneck_level"] = (
            None if dg is None else dg.get("bottleneck_level"))
        out["diagnostics_acf"] = (
            None if dg is None
            else dg.get("asymptotic_convergence_factor"))
        out["diagnostics_ok"] = bool(
            dg is not None and dg.get("bottleneck_level") is not None
            and all(r.get("level_reduction") is not None
                    for r in dg.get("levels", [])))
    except Exception as e:  # pragma: no cover - bench robustness
        out["diagnostics_error"] = str(e)[:200]
        out["diagnostics_ok"] = False

    # ---- classical replay ---------------------------------------------
    try:
        Ac = amgx.gallery.poisson("7pt", n_classical, n_classical,
                                  n_classical).init()
        bc = jnp.ones(Ac.num_rows)
        slc = amgx.create_solver(_classical_cfg())
        slc.setup(Ac)
        resc = slc.solve(bc)
        repc = resc.report.to_dict()
        errsc = validate_report(repc)
        out[f"classical_{n_classical}^3_report"] = repc
        out["classical_report_valid"] = not errsc
        if errsc:
            out["classical_report_schema_errors"] = errsc[:10]
    except Exception as e:  # pragma: no cover - bench robustness
        out["classical_error"] = str(e)[:200]

    # ---- counter dump + Perfetto span export --------------------------
    out["counters"] = metrics.snapshot()
    trace_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_obs_trace.json")
    out["perfetto_events"] = spans.export_chrome_trace(trace_path)
    out["perfetto_trace"] = os.path.basename(trace_path)
    try:
        with open(trace_path) as f:
            doc = json.load(f)
        out["perfetto_valid"] = bool(
            isinstance(doc.get("traceEvents"), list)
            and len(doc["traceEvents"]) == out["perfetto_events"])
    except Exception as e:  # pragma: no cover - bench robustness
        out["perfetto_valid"] = False
        out["perfetto_error"] = str(e)[:120]

    # ---- serving tracing replay ---------------------------------------
    # request-path tracing (serving_tracing) on vs off over the SAME
    # serving load: the per-ticket lifecycle spans + flow tagging are
    # host-side dict appends, so the paired-median per-request cost
    # must stay within 2%. Runs AFTER the full-timeline export above,
    # and resets the span buffer post-warmup, so BENCH_obs_requests
    # carries ONLY the burst's request chains — a per-request
    # artifact, not a second copy of the whole solver timeline.
    try:
        from amgx_tpu.presets import SERVING_CG
        from amgx_tpu.serving import SolveService

        ns = 20
        As = amgx.gallery.poisson("7pt", ns, ns, ns).init()
        rng = np.random.default_rng(11)
        bsrv = [rng.standard_normal(As.num_rows) for _ in range(6)]

        def _svc(tracing):
            return SolveService(Config.from_string(
                SERVING_CG + ", serving_bucket_slots=4,"
                f" serving_chunk_iters=8, serving_tracing={tracing}"))

        svc_on, svc_off = _svc(1), _svc(0)
        for svc in (svc_on, svc_off):     # build bucket + warm traces
            for b_ in bsrv[:4]:
                svc.submit(As, b_)
            svc.drain(timeout_s=300)

        def _burst(svc):
            t0 = time.perf_counter()
            ts = [svc.submit(As, b_) for b_ in bsrv]
            svc.drain(timeout_s=300)
            assert all(t.done and t.result.converged for t in ts)
            return (time.perf_counter() - t0) / len(bsrv)

        spans.reset()       # requests-only artifact from here on
        tr_ratios = []
        for _ in range(reps):
            tr_ratios.append(_burst(svc_on) / _burst(svc_off))
        tr_ratios.sort()
        out["serving_trace_overhead_pct"] = round(
            100.0 * (tr_ratios[len(tr_ratios) // 2] - 1.0), 2)
        out["serving_trace_overhead_pair_spread"] = [
            round(100.0 * (tr_ratios[0] - 1.0), 2),
            round(100.0 * (tr_ratios[-1] - 1.0), 2)]
        out["serving_trace_ok"] = bool(
            abs(out["serving_trace_overhead_pct"]) <= 2.0)
        req_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_obs_requests.json")
        out["serving_trace_events"] = spans.export_chrome_trace(
            req_path)
        with open(req_path) as f:
            reqdoc = json.load(f)
        flows = [e for e in reqdoc["traceEvents"]
                 if e.get("cat") == "trace.flow"]
        starts = sum(1 for e in flows if e["ph"] == "s")
        out["serving_trace_flow_events"] = len(flows)
        out["serving_trace_flow_chains"] = starts
        out["serving_trace_artifact"] = os.path.basename(req_path)
        # every traced burst request must have minted a flow chain
        out["serving_trace_flows_ok"] = bool(
            starts >= len(bsrv) and len(flows) > 2 * starts)
    except Exception as e:  # pragma: no cover - bench robustness
        out["serving_trace_error"] = str(e)[:200]
        out["serving_trace_ok"] = False
    return out


# artifact schema: version 2 adds the `round`/`schema_version` stamps
# (tools/bench_history.py keys rounds on them instead of parsing
# filenames) and the incremental checkpoint writes below
BENCH_SCHEMA_VERSION = 2


def _round_stamp():
    """Stable round id for the artifact: the driver exports
    AMGX_BENCH_ROUND when it knows the round number; None otherwise
    (bench_history falls back to the wrapper's `n`, then filename)."""
    import os
    r = os.environ.get("AMGX_BENCH_ROUND", "").strip()
    if not r:
        return None
    return int(r) if r.isdigit() else r


def _write_artifact(payload):
    """(Re)write BENCH.json. Called after EVERY phase, not only at the
    end of main(): a round whose process dies mid-run (driver timeout,
    OOM) still leaves the completed phases' numbers on disk instead of
    an unrecorded round — the regression sentinel then sees a partial
    round, not a hole."""
    import os
    art = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH.json")
    with open(art, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")


def main():
    t_start = time.perf_counter()
    amgx.initialize()
    extra = {}
    spmv_gbps, spmv_s = 0.0, 1.0
    _round = _round_stamp()

    def _checkpoint(metric="bench_incomplete", value=-1.0, unit="none",
                    error=None):
        payload = {
            "schema_version": BENCH_SCHEMA_VERSION,
            "round": _round,
            "metric": metric,
            "value": value,
            "unit": unit,
            "vs_baseline": round(spmv_gbps / A100_HBM_GBPS, 4),
            "extra": extra,
        }
        if error is not None:
            payload["error"] = str(error)[:300]
        elif metric == "bench_incomplete":
            payload["error"] = "incomplete: process ended mid-run " \
                               "(checkpoint write)"
        try:
            _write_artifact(payload)
        except Exception as e:  # pragma: no cover - bench robustness
            extra["artifact_error"] = str(e)[:120]
        return payload
    try:
        sp = bench_spmv_vs_ceiling()
        spmv_gbps, spmv_s = sp["gbps"], sp["ms"] / 1e3
        extra["spmv_7pt_128^3_f32_gbps"] = round(sp["gbps"], 2)
        extra["spmv_7pt_128^3_f32_ms"] = round(sp["ms"], 4)
        extra["stream_ceiling_gbps"] = round(sp["ceiling_gbps"], 2)
        extra["spmv_vs_ceiling"] = round(sp["ratio_median"], 3)
        extra["spmv_vs_ceiling_spread"] = [round(sp["ratio_min"], 3),
                                           round(sp["ratio_max"], 3)]
    except Exception as e:  # pragma: no cover - bench robustness
        extra["spmv_error"] = str(e)[:120]
    _checkpoint()
    # every optional phase runs under a SIGALRM guard so the single
    # JSON line always prints
    import signal

    class _Budget(Exception):
        pass

    def _on_alarm(*_a):  # pragma: no cover - timing dependent
        raise _Budget()

    import gc

    # classical lines first (cheap since the host-path rework: ~3 s at
    # 64^3, ~20 s warm at 128^3); the 256^3 north star runs LAST with
    # the largest alarm — an aborted 256^3 phase must never poison the
    # other measurements (eager leftovers degrade later transfers).
    for cn in (64, 128):
        if time.perf_counter() - t_start > 900:   # alarm-abort pile-up
            extra[f"classical_{cn}_error"] = "skipped: out of budget"
            continue
        try:
            old = signal.signal(signal.SIGALRM, _on_alarm)
            signal.alarm(300)
            try:
                cr = bench_classical(cn)
                extra.update({
                    f"classical_pmis_d2_{cn}^3_setup_warm_s":
                        round(cr["setup_warm_s"], 2),
                    f"classical_pmis_d2_{cn}^3_setup_rows_per_s":
                        round(cr["setup_rows_per_s"]),
                    f"classical_pmis_d2_{cn}^3_setup_accounted_fraction":
                        round(cr["setup_accounted_fraction"], 3),
                    f"classical_pmis_d2_{cn}^3_solve_s":
                        round(cr["solve_s"], 3),
                    f"classical_pmis_d2_{cn}^3_iters": cr["iters"],
                    f"classical_pmis_d2_{cn}^3_true_rel_residual":
                        cr["rel"],
                })
                extra[f"classical_{cn}^3_config_fallback"] = \
                    cr["config_fallback"]
                extra[f"classical_{cn}^3_smoother"] = \
                    cr["smoother_effective"]
                if cn == 128:
                    extra["classical_128^3_setup_breakdown"] = \
                        cr["breakdown"]
                    # sentinel-tracked aliases (tools/bench_history.py
                    # SERIES): the 24x classical-vs-flagship gap's two
                    # headline walls, declared from this round forward
                    extra["classical_128^3_setup_s"] = \
                        round(cr["setup_warm_s"], 2)
                    extra["classical_128^3_solve_s"] = \
                        round(cr["solve_s"], 3)
                    # plan-split RAP attribution (sentinel-tracked):
                    # the summed per-level RAP spans of the warm setup
                    extra["classical_128^3_rap_s"] = cr["rap_s"]
                    extra["classical_128^3_rap_share"] = \
                        cr["rap_share"]
            finally:
                signal.alarm(0)
                signal.signal(signal.SIGALRM, old)
        except _Budget:  # pragma: no cover - timing dependent
            extra[f"classical_{cn}_error"] = "wall-clock budget exceeded"
            break
        except Exception as e:  # pragma: no cover - bench robustness
            extra[f"classical_{cn}_error"] = str(e)[:200]
            break
    _checkpoint()
    gc.collect()

    # spmv layout-efficiency phase (DIA/ELL/SWELL, fused vs unfused):
    # the tentpole's one-pass win as a recorded number per round
    try:
        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(240)
        try:
            extra["spmv_layouts_128^3"] = bench_spmv_layouts()
            fl_row = extra["spmv_layouts_128^3"].get(
                "dia_smooth2_residual", {})
            if "fused_speedup" in fl_row:
                extra["fused_smooth_residual_speedup"] = \
                    fl_row["fused_speedup"]
            cy_row = extra["spmv_layouts_128^3"].get(
                "geo_cycle_64^3", {})
            if "speedup" in cy_row:
                extra["fused_cycle_speedup_64^3"] = cy_row["speedup"]
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    except _Budget:  # pragma: no cover - timing dependent
        extra["spmv_layouts_error"] = "wall-clock budget exceeded"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["spmv_layouts_error"] = str(e)[:200]
    _checkpoint()
    gc.collect()

    # Krylov-shell phase: paired krylov_fusion=1 vs 0 replay (PCG +
    # GEO AMG) — the fused SpMV+dot / cg_update shell's warm-solve
    # speedup plus the per-iteration HBM pass census
    try:
        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(300)
        try:
            kr = bench_krylov()
            extra["krylov_shell"] = kr
            extra["krylov_fused_speedup"] = \
                kr["krylov_fused_speedup"]
            extra["krylov_fused_passes"] = kr["krylov_fused_passes"]
            extra["krylov_unfused_passes"] = \
                kr["krylov_unfused_passes"]
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    except _Budget:  # pragma: no cover - timing dependent
        extra["krylov_error"] = "wall-clock budget exceeded"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["krylov_error"] = str(e)[:200]
    _checkpoint()
    gc.collect()

    # batched-serving phase: cheap (32^3, f64 CG+AggAMG), guarded like
    # the other optional phases so the JSON line always prints
    try:
        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(240)
        try:
            extra["batched_32^3_per_system"] = bench_batched()
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    except _Budget:  # pragma: no cover - timing dependent
        extra["batched_error"] = "wall-clock budget exceeded"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["batched_error"] = str(e)[:200]
    _checkpoint()
    gc.collect()

    # serving phase: open-loop load against the continuous-batching
    # solve service — sustained solves/sec, p50/p99 latency, cache-hit
    # rate, zero-retrace-after-AOT and deadline-miss proof (nested
    # payload -> artifact; scalar headlines -> compact line)
    try:
        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(240)
        try:
            sv = bench_serving()
            extra["serving"] = sv
            extra["serving_solves_per_s"] = sv["solves_per_s"]
            extra["serving_p50_ms"] = sv["p50_ms"]
            extra["serving_p99_ms"] = sv["p99_ms"]
            extra["serving_cache_hit_rate"] = sv["cache_hit_rate"]
            extra["serving_retraces_after_warmup"] = \
                sv["retraces_after_warmup"]
            extra["serving_deadline_ok"] = sv["deadline_statuses_ok"]
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    except _Budget:  # pragma: no cover - timing dependent
        extra["serving_error"] = "wall-clock budget exceeded"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["serving_error"] = str(e)[:200]
    _checkpoint()
    gc.collect()

    # fleet phase: 2-replica fingerprint-affine router vs one replica
    # of the identical config under the cache-capacity wave load —
    # scaling ratio, route-counter affinity proof, shed accuracy at 2x
    # saturation (nested payload -> artifact; gates -> compact line)
    try:
        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(300)
        try:
            fl = bench_fleet()
            extra["fleet"] = fl
            extra["fleet_scaling_x"] = fl["fleet_scaling_x"]
            extra["fleet_scaling_efficiency"] = \
                fl["fleet_scaling_efficiency"]
            extra["fleet_p99_at_2x_ms"] = fl["fleet_p99_at_2x_ms"]
            extra["fleet_affinity_rate"] = fl["fleet_affinity_rate"]
            extra["fleet_failover_wall_s"] = \
                fl["fleet_failover_wall_s"]
            extra["fleet_failover_lost_requests"] = \
                fl["fleet_failover_lost_requests"]
            extra["fleet_ok"] = fl["fleet_ok"]
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    except _Budget:  # pragma: no cover - timing dependent
        extra["fleet_error"] = "wall-clock budget exceeded"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["fleet_error"] = str(e)[:200]
    _checkpoint()
    gc.collect()

    # chaos phase: serving fault tolerance — kill-and-recover wall
    # (journal replay + persisted hierarchies + AOT: zero full setups,
    # zero retraces, bit-identical resume), scripted fault scenarios
    # all-terminal, shed accuracy at 2x saturation
    try:
        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(300)
        try:
            ch = bench_chaos()
            extra["chaos"] = ch
            extra["chaos_recover_wall_s"] = ch["chaos_recover_wall_s"]
            extra["chaos_accepted_p99_ms"] = \
                ch["chaos_accepted_p99_ms"]
            extra["chaos_all_terminal"] = ch["chaos_all_terminal"]
            extra["chaos_recover_bitwise_ok"] = \
                ch["recover_bitwise_ok"]
            extra["chaos_shed_ok"] = ch["shed_ok"]
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    except _Budget:  # pragma: no cover - timing dependent
        extra["chaos_error"] = "wall-clock budget exceeded"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["chaos_error"] = str(e)[:200]
    _checkpoint()
    gc.collect()

    # resilience smoke phase: guarded vs unguarded iteration-loop cost
    # (BENCH_* tracks that the health guards stay within 2% of baseline)
    try:
        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(180)
        try:
            extra["resilience_32^3"] = bench_resilience()
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    except _Budget:  # pragma: no cover - timing dependent
        extra["resilience_error"] = "wall-clock budget exceeded"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["resilience_error"] = str(e)[:200]
    _checkpoint()
    gc.collect()

    # observability phase: instrumented flagship+classical replays with
    # the full SolveReport + counter dump recorded in the artifact, the
    # telemetry-on-vs-off paired overhead gate, and the Perfetto span
    # export (nested payload -> artifact; scalar gates -> compact line)
    try:
        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(300)
        try:
            obs = bench_obs(reps=5)
            extra["obs"] = obs
            extra["obs_overhead_pct"] = obs.get("overhead_pct")
            extra["obs_overhead_ok"] = obs.get("overhead_ok")
            extra["obs_report_valid"] = bool(
                obs.get("flagship_report_valid")
                and obs.get("classical_report_valid", True))
            extra["obs_perfetto_valid"] = obs.get("perfetto_valid")
            extra["obs_perfetto_events"] = obs.get("perfetto_events")
            extra["obs_diagnostics_ok"] = obs.get("diagnostics_ok")
            extra["obs_diagnostics_bottleneck_level"] = \
                obs.get("diagnostics_bottleneck_level")
            extra["serving_trace_overhead_pct"] = \
                obs.get("serving_trace_overhead_pct")
            extra["serving_trace_ok"] = obs.get("serving_trace_ok")
            extra["serving_trace_flow_chains"] = \
                obs.get("serving_trace_flow_chains")
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    except _Budget:  # pragma: no cover - timing dependent
        extra["obs_error"] = "wall-clock budget exceeded"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["obs_error"] = str(e)[:200]
    _checkpoint()
    gc.collect()

    try:
        fl = bench_flagship()
        solve_s = fl["solve_s"]
        extra.update({
            "flagship_128^3_setup_cold_s": round(fl["setup_cold_s"], 2),
            "flagship_128^3_setup_warm_s": round(fl["setup_warm_s"], 3),
            "flagship_128^3_setup_rows_per_s":
                round(fl["setup_rows_per_s"]),
            "flagship_128^3_setup_accounted_fraction":
                round(fl["setup_accounted_fraction"], 3),
            "flagship_128^3_setup_attribution_ok":
                bool(fl["setup_accounted_fraction"] >= 0.9),
            "flagship_128^3_resetup_s": round(fl["resetup_s"], 3),
            "flagship_128^3_resetup_first_s":
                round(fl["resetup_first_s"], 3),
            # trajectory guard for the trace-reuse fix: the FIRST
            # resetup now replays the setup's compiled pieces, so this
            # ratio stays O(1) instead of the old fused-jit retrace blowup
            "flagship_128^3_resetup_first_over_steady": round(
                fl["resetup_first_s"] / max(fl["resetup_s"], 1e-9), 1),
            "flagship_128^3_setup_breakdown": fl["breakdown"],
            "flagship_128^3_solve_s": round(solve_s, 4),
            "flagship_128^3_outer_iters": fl["iters"],
            "flagship_128^3_converged": fl["converged"],
            "flagship_128^3_true_rel_residual": fl["rel"],
            # solve-phase attribution: per-level cycle breakdown +
            # per-cycle kernel counts (nested -> artifact only) and the
            # fused-vs-unfused cycle speedup scalar (compact line too)
            "flagship_128^3_cycle_breakdown": fl["cycle_breakdown"],
            "flagship_128^3_cycle_speedup": fl["cycle_speedup"],
            "flagship_128^3_cycle_fused_speedup":
                (fl["cycle_speedup"] or {}).get("speedup"),
            "flagship_config":
                "REFINEMENT[f64] -> FGMRES+GEO-AggAMG[f32]+Cheb2",
        })
        value = solve_s
        metric = "poisson7pt_128^3 refined FGMRES+AggAMG solve to 1e-8 (f64)"
        unit = "s"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["flagship_error"] = str(e)[:200]
        if "spmv_error" in extra:
            # neither phase produced a real measurement — say so rather
            # than reporting the spmv placeholder as a timing
            value, metric, unit = -1.0, "bench_failed", "none"
        else:
            value = spmv_s * 1e3
            metric = "poisson7pt_128^3 SpMV"
            unit = "ms"
    _checkpoint(metric=metric, value=value, unit=unit,
                error="incomplete: north-star phase still pending")

    # plan-split RAP phase: paired plan-vs-eager warm-setup replay
    # (flagship GEO + classical) — the spgemm_plan knob's measured win;
    # sentinel-tracked via spgemm_plan_speedup
    try:
        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(420)
        try:
            sg = bench_spgemm_plan()
            extra["spgemm"] = sg
            extra["spgemm_plan_speedup"] = sg["spgemm_plan_speedup"]
            extra["spgemm_plan_speedup_classical"] = \
                sg["spgemm_plan_speedup_classical"]
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    except _Budget:  # pragma: no cover - timing dependent
        extra["spgemm_error"] = "wall-clock budget exceeded"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["spgemm_error"] = str(e)[:200]
    _checkpoint()
    gc.collect()

    # mixed-precision phase: the flagship paired-replayed at
    # solve_precision=float vs bfloat16 (ROADMAP item 5: bf16 operand
    # slabs through the fused kernels inside the f64 refinement
    # shell); sentinel-tracked via flagship_128^3_solve_bf16_s +
    # mixed_precision_speedup
    try:
        old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(420)
        try:
            mp = bench_precision(reps=3)
            extra["precision"] = mp
            extra["flagship_128^3_solve_bf16_s"] = mp["solve_bf16_s"]
            extra["mixed_precision_speedup"] = \
                mp["mixed_precision_speedup"]
            extra["mixed_precision_matched_residuals"] = \
                mp["matched_residuals_ok"]
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    except _Budget:  # pragma: no cover - timing dependent
        extra["precision_error"] = "wall-clock budget exceeded"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["precision_error"] = str(e)[:200]
    _checkpoint()
    gc.collect()

    # the 256^3 north star (BASELINE.md headline). Solo phase cost with
    # a cold compile cache is ~500 s (gallery + one cold setup + the
    # fused-resetup trace); warm-cache runs are far cheaper. light mode
    # folds the resetup into the warm solver.
    if time.perf_counter() - t_start < 1100:
        try:
            old = signal.signal(signal.SIGALRM, _on_alarm)
            signal.alarm(720)
            try:
                ns = bench_flagship(256, tolerance="1e-10", reps=1,
                                    light=True)
                extra.update({
                    "northstar_256^3_setup_cold_s":
                        round(ns["setup_cold_s"], 2),
                    "northstar_256^3_setup_warm_s":
                        round(ns["setup_warm_s"], 2),
                    "northstar_256^3_setup_rows_per_s":
                        round(ns["setup_rows_per_s"]),
                    "northstar_256^3_setup_accounted_fraction":
                        round(ns["setup_accounted_fraction"], 3),
                    # per-stage attribution of the 256^3 warm setup:
                    # round 5's 17.37 s regression was unattributable
                    # because only the 128^3 breakdown was recorded
                    "northstar_256^3_setup_breakdown": ns["breakdown"],
                    "northstar_256^3_resetup_s": round(ns["resetup_s"], 3),
                    "northstar_256^3_resetup_first_s":
                        round(ns["resetup_first_s"], 3),
                    "northstar_256^3_solve_s": round(ns["solve_s"], 3),
                    "northstar_256^3_outer_iters": ns["iters"],
                    "northstar_256^3_converged": ns["converged"],
                    "northstar_256^3_true_rel_residual": ns["rel"],
                    "northstar_256^3_cycle_breakdown":
                        ns["cycle_breakdown"],
                    "northstar_256^3_cycle_speedup": ns["cycle_speedup"],
                    "northstar_256^3_cycle_fused_speedup":
                        (ns["cycle_speedup"] or {}).get("speedup"),
                })
            finally:
                signal.alarm(0)
                signal.signal(signal.SIGALRM, old)
        except _Budget:  # pragma: no cover - timing dependent
            extra["northstar_error"] = "wall-clock budget exceeded"
        except Exception as e:  # pragma: no cover - bench robustness
            extra["northstar_error"] = str(e)[:200]

    # full payload -> BENCH.json artifact (machine-readable by contract:
    # json.load must work; already checkpoint-written after every phase
    # above — this is the final, complete, error-free write); stdout
    # gets ONE COMPACT line — scalars only, no nested breakdowns —
    # because the driver's stdout-tail capture is bounded and round 5's
    # full-fat line outgrew it (parsed: null, the SpMV-efficiency /
    # 64^3 / classical headline numbers lost).
    _checkpoint(metric=metric, value=value, unit=unit)
    compact = {k: v for k, v in extra.items()
               if not isinstance(v, (dict, list))}
    print(json.dumps({
        "schema_version": BENCH_SCHEMA_VERSION,
        "round": _round,
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": round(spmv_gbps / A100_HBM_GBPS, 4),
        "artifact": "BENCH.json",
        "extra": compact,
    }), flush=True)


if __name__ == "__main__":
    import sys

    if sys.argv[1:] == ["setup"]:
        # standalone setup-attribution phase: `python bench.py setup`
        amgx.initialize()
        res = bench_setup()
        worst = min(v["setup_accounted_fraction"] for v in res.values())
        print(json.dumps({
            "metric": "flagship warm setup attribution "
                      "(accounted fraction, worst grid)",
            "value": worst,
            "unit": "fraction",
            "vs_baseline": 0.0,
            "extra": res,
        }), flush=True)
    elif sys.argv[1:] == ["spmv"]:
        # standalone layout-efficiency phase: `python bench.py spmv`
        amgx.initialize()
        res = bench_spmv_layouts()
        headline = res.get("dia_smooth2_residual", {}).get(
            "fused_speedup", 0.0)
        print(json.dumps({
            "metric": "fused smooth(2)+residual speedup vs unfused "
                      "(poisson7pt 128^3 DIA)",
            "value": headline,
            "unit": "x",
            "vs_baseline": res.get("dia", {}).get("vs_ceiling", 0.0),
            "extra": res,
        }), flush=True)
    elif sys.argv[1:2] == ["precision"]:
        # standalone mixed-precision phase: `python bench.py precision`
        # (optionally `--smoke` at 32^3 for a fast functional check) —
        # flagship paired replay at solve_precision=float vs bfloat16
        amgx.initialize()
        smoke = "--smoke" in sys.argv[2:]
        res = bench_precision(n=32 if smoke else 128,
                              reps=3 if smoke else 5)
        print(json.dumps({
            "metric": "flagship solve_precision float/bfloat16 "
                      "paired-replay speedup",
            "value": res.get("mixed_precision_speedup", -1.0),
            "unit": "x",
            "vs_baseline": 0.0,
            "extra": res,
        }), flush=True)
    elif sys.argv[1:] == ["obs"]:
        # standalone observability phase: `python bench.py obs` —
        # instrumented replays, full reports + counter dump into the
        # BENCH_obs.json artifact, Perfetto span export, overhead gate
        amgx.initialize()
        res = bench_obs()
        try:
            import os
            art = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_obs.json")
            with open(art, "w") as f:
                json.dump(res, f, indent=1)
                f.write("\n")
        except Exception as e:  # pragma: no cover - bench robustness
            res["artifact_error"] = str(e)[:120]
        compact = {k: v for k, v in res.items()
                   if not isinstance(v, (dict, list))}
        print(json.dumps({
            "metric": "telemetry-instrumented flagship per-iteration "
                      "overhead vs telemetry=0 (paired median)",
            "value": res.get("overhead_pct", -1.0),
            "unit": "pct",
            "vs_baseline": 0.0,
            "artifact": "BENCH_obs.json",
            "extra": compact,
        }), flush=True)
    elif sys.argv[1:2] == ["serving"]:
        # standalone serving phase: `python bench.py serving` (full) or
        # `python bench.py serving --smoke` (the tier-1 fast path:
        # tiny grids, arrival schedule collapsed)
        amgx.initialize()
        res = bench_serving(smoke="--smoke" in sys.argv[2:])
        # round stamp + series-named scalars: tools/bench_history.py
        # reads phase artifacts directly, so a standalone run recorded
        # under AMGX_BENCH_ROUND populates the serving_* series even
        # when no BENCH_r<NN>.json wrapper carried them
        res["round"] = _round_stamp()
        res["extra"] = {
            "serving_solves_per_s": res["solves_per_s"],
            "serving_p50_ms": res["p50_ms"],
            "serving_p99_ms": res["p99_ms"],
            "serving_cache_hit_rate": res["cache_hit_rate"],
        }
        try:
            import os
            art = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_serving.json")
            with open(art, "w") as f:
                json.dump(res, f, indent=1)
                f.write("\n")
        except Exception as e:  # pragma: no cover - bench robustness
            res["artifact_error"] = str(e)[:120]
        print(json.dumps({
            "metric": "serving sustained throughput under open-loop "
                      "load (continuous batching)",
            "value": res["solves_per_s"],
            "unit": "solves/s",
            "vs_baseline": 0.0,
            "artifact": "BENCH_serving.json",
            "extra": {k: v for k, v in res.items()
                      if not isinstance(v, (dict, list))},
        }), flush=True)
    elif sys.argv[1:2] == ["fleet"]:
        # standalone fleet phase: `python bench.py fleet` (full) or
        # `python bench.py fleet --smoke` (tier-1 fast path: tiny
        # grids, short waves) — 2-replica scaling, affinity, 2x shed
        amgx.initialize()
        res = bench_fleet(smoke="--smoke" in sys.argv[2:])
        res["round"] = _round_stamp()
        res["extra"] = {
            "fleet_scaling_x": res["fleet_scaling_x"],
            "fleet_scaling_efficiency":
                res["fleet_scaling_efficiency"],
            "fleet_p99_at_2x_ms": res["fleet_p99_at_2x_ms"],
            "fleet_affinity_rate": res["fleet_affinity_rate"],
            "fleet_solves_per_s": res["fleet_solves_per_s"],
            "fleet_failover_wall_s": res["fleet_failover_wall_s"],
            "fleet_failover_lost_requests":
                res["fleet_failover_lost_requests"],
        }
        try:
            import os
            art = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_fleet.json")
            with open(art, "w") as f:
                json.dump(res, f, indent=1)
                f.write("\n")
        except Exception as e:  # pragma: no cover - bench robustness
            res["artifact_error"] = str(e)[:120]
        print(json.dumps({
            "metric": "fleet 2-replica vs single-replica sustained "
                      "throughput (fingerprint-affine router, "
                      "cache-capacity wave load)",
            "value": res["fleet_scaling_x"],
            "unit": "x",
            "vs_baseline": 0.0,
            "artifact": "BENCH_fleet.json",
            "extra": {k: v for k, v in res.items()
                      if not isinstance(v, (dict, list))},
        }), flush=True)
    elif sys.argv[1:2] == ["autotune"]:
        # standalone autotune phase: `python bench.py autotune` (full)
        # or `python bench.py autotune --smoke` (tier-1 fast path:
        # tiny grid, short paired loop) — the online tuner's win
        # (mistuned hot fingerprint re-served >=2x faster after
        # promotion) and its cost (paired saturated p99 within noise,
        # zero deadline misses added by the search)
        amgx.initialize()
        res = bench_autotune(smoke="--smoke" in sys.argv[2:])
        res["round"] = _round_stamp()
        res["extra"] = {
            "autotune_speedup": res["autotune_speedup"],
            "autotune_shadow_p99_impact_pct":
                res["autotune_shadow_p99_impact_pct"],
        }
        try:
            import os
            art = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_autotune.json")
            with open(art, "w") as f:
                json.dump(res, f, indent=1)
                f.write("\n")
        except Exception as e:  # pragma: no cover - bench robustness
            res["artifact_error"] = str(e)[:120]
        print(json.dumps({
            "metric": "autotuner speedup on a mistuned hot "
                      "fingerprint (min of iteration and wall "
                      "ratios, measured post-promotion)",
            "value": res["autotune_speedup"],
            "unit": "x",
            "vs_baseline": 0.0,
            "artifact": "BENCH_autotune.json",
            "extra": {k: v for k, v in res.items()
                      if not isinstance(v, (dict, list))},
        }), flush=True)
    elif sys.argv[1:2] == ["chaos"]:
        # standalone chaos phase: `python bench.py chaos` (full) or
        # `python bench.py chaos --smoke` (tier-1 fast path)
        amgx.initialize()
        res = bench_chaos(smoke="--smoke" in sys.argv[2:])
        try:
            import os
            art = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_chaos.json")
            with open(art, "w") as f:
                json.dump(res, f, indent=1)
                f.write("\n")
        except Exception as e:  # pragma: no cover - bench robustness
            res["artifact_error"] = str(e)[:120]
        print(json.dumps({
            "metric": "serving kill-and-recover wall (journal replay "
                      "+ persisted hierarchies + AOT warm start)",
            "value": res["chaos_recover_wall_s"],
            "unit": "s",
            "vs_baseline": 0.0,
            "artifact": "BENCH_chaos.json",
            "extra": {k: v for k, v in res.items()
                      if not isinstance(v, (dict, list))},
        }), flush=True)
    elif sys.argv[1:2] == ["spgemm"]:
        # standalone plan-split RAP phase: `python bench.py spgemm`
        # (full: flagship 128^3 + classical 64^3 paired warm-setup
        # replay) or `--smoke` (tiny grids, tier-1 functional check)
        amgx.initialize()
        smoke = "--smoke" in sys.argv[2:]
        res = bench_spgemm_plan(
            flagship_n=32 if smoke else 128,
            classical_n=16 if smoke else 64,
            reps=1 if smoke else 2)
        try:
            import os
            art = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_spgemm.json")
            with open(art, "w") as f:
                json.dump(res, f, indent=1)
                f.write("\n")
        except Exception as e:  # pragma: no cover - bench robustness
            res["artifact_error"] = str(e)[:120]
        print(json.dumps({
            "metric": "plan-split vs eager Galerkin RAP warm-setup "
                      "speedup (paired replay, flagship)",
            "value": res.get("spgemm_plan_speedup", -1.0),
            "unit": "x",
            "vs_baseline": 0.0,
            "artifact": "BENCH_spgemm.json",
            "extra": {k: v for k, v in res.items()
                      if not isinstance(v, (dict, list))},
        }), flush=True)
    elif sys.argv[1:2] == ["matfree"]:
        # standalone matrix-free phase: `python bench.py matfree`
        # (full: 128^3 paired replay) or `--smoke` (16^3, the tier-1
        # functional check — must exit 0)
        amgx.initialize()
        smoke = "--smoke" in sys.argv[2:]
        res = bench_matfree(n=16 if smoke else 128,
                            reps=1 if smoke else 3, smoke=smoke)
        res["round"] = _round_stamp()
        res["extra"] = {
            "matrix_free_cycle_speedup":
                res["matrix_free_cycle_speedup"],
            "matrix_free_level_bytes_ratio":
                res["matrix_free_level_bytes_ratio"],
        }
        try:
            import os
            art = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_matfree.json")
            with open(art, "w") as f:
                json.dump(res, f, indent=1)
                f.write("\n")
        except Exception as e:  # pragma: no cover - bench robustness
            res["artifact_error"] = str(e)[:120]
        print(json.dumps({
            "metric": "matrix-free vs slab warm cycle speedup "
                      "(paired replay, GEO)",
            "value": res["matrix_free_cycle_speedup"],
            "unit": "x",
            "vs_baseline": 0.0,
            "artifact": "BENCH_matfree.json",
            "extra": {k: v for k, v in res.items()
                      if not isinstance(v, (dict, list))},
        }), flush=True)
    elif sys.argv[1:2] == ["krylov"]:
        # standalone Krylov-shell phase: `python bench.py krylov`
        # (full: 128^3 paired replay, + northstar 256^3 on TPU) or
        # `--smoke` (16^3, the tier-1 functional check — must exit 0)
        amgx.initialize()
        smoke = "--smoke" in sys.argv[2:]
        res = bench_krylov(n=16 if smoke else 128,
                           reps=1 if smoke else 3, smoke=smoke)
        res["round"] = _round_stamp()
        res["extra"] = {
            "krylov_fused_speedup": res["krylov_fused_speedup"],
            "krylov_fused_passes": res["krylov_fused_passes"],
            "krylov_unfused_passes": res["krylov_unfused_passes"],
        }
        try:
            import os
            art = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_krylov.json")
            with open(art, "w") as f:
                json.dump(res, f, indent=1)
                f.write("\n")
        except Exception as e:  # pragma: no cover - bench robustness
            res["artifact_error"] = str(e)[:120]
        print(json.dumps({
            "metric": "fused vs unfused Krylov-shell warm solve "
                      "speedup (paired replay, PCG+AMG)",
            "value": res["krylov_fused_speedup"],
            "unit": "x",
            "vs_baseline": 0.0,
            "artifact": "BENCH_krylov.json",
            "extra": {k: v for k, v in res.items()
                      if not isinstance(v, (dict, list))},
        }), flush=True)
    elif sys.argv[1:] == ["resilience"]:
        # standalone smoke phase: `python bench.py resilience`
        amgx.initialize()
        res = bench_resilience()
        print(json.dumps({
            "metric": "resilience guarded-vs-unguarded CG iteration "
                      "overhead (poisson7pt 32^3)",
            "value": res["overhead_pct"],
            "unit": "pct",
            "vs_baseline": 0.0,
            "extra": res,
        }), flush=True)
    else:
        main()
