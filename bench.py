"""Benchmark entry point (run on the real TPU chip by the driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

Headline: FGMRES + aggregation-AMG solve wall-clock on a 7-pt Poisson
(the BASELINE.md north-star configuration, scaled to one chip).
`vs_baseline` is measured against the reference's roofline on its own
hardware: AmgX SpMV is HBM-bandwidth-bound, so we report our achieved
SpMV bandwidth as a fraction of A100 peak (1555 GB/s) — the honest
single-chip proxy until a side-by-side A100 run exists (the reference
repo publishes no benchmark tables, BASELINE.md).
"""
from __future__ import annotations

import json
import time

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/amgx_tpu_jax_cache_tpu")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import amgx_tpu as amgx  # noqa: E402
from amgx_tpu.config import Config  # noqa: E402

A100_HBM_GBPS = 1555.0  # A2 SXM A100-40GB peak memory bandwidth


def bench_spmv(n: int = 128, reps: int = 50):
    """SpMV GB/s on 7-pt Poisson n^3 (ELL layout, float32 values +
    float32 compute: the bandwidth-bound regime the reference's csrmv
    lives in)."""
    A = amgx.gallery.poisson("7pt", n, n, n, dtype=np.float32).init()
    x = jnp.ones(A.num_rows, jnp.float32)

    @jax.jit
    def loop(x):
        def body(_, x):
            return amgx.ops.spmv(A, x) * (1.0 / 6.0)
        return jax.lax.fori_loop(0, reps, body, x)

    loop(x).block_until_ready()              # compile
    t0 = time.perf_counter()
    loop(x).block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    # honest bytes model: each value read once, x read once, y written
    # once (the Pallas DIA kernel achieves exactly this traffic)
    n_rows = A.num_rows
    if A.dia_vals is not None:
        k = len(A.dia_offsets)
        bytes_moved = (k * n_rows + 2 * n_rows) * 4
    else:
        bytes_moved = A.ell_cols.size * (4 + 4) + A.num_rows * 4 * 2
    return bytes_moved / dt / 1e9, dt


def bench_stream_ceiling():
    """Measured streaming ceiling of this rig (read+write of a 256 MB
    array inside one compiled loop) — the honest denominator for SpMV
    efficiency when the chip sits behind a bandwidth-limited tunnel."""
    rows = 256 * 1024 * 1024 // (128 * 4)
    v = jnp.ones((rows, 128), jnp.float32)

    @jax.jit
    def loop(v):
        return jax.lax.fori_loop(0, 10, lambda _, x: x * 1.000001, v)

    loop(v).block_until_ready()
    t0 = time.perf_counter()
    loop(v).block_until_ready()
    dt = (time.perf_counter() - t0) / 10
    return 2 * rows * 128 * 4 / dt / 1e9


def bench_fgmres_amg(n: int = 32):
    """FGMRES + aggregation-AMG to 1e-6 relative on 7-pt Poisson n^3
    (FGMRES_AGGREGATION.json — milestone config 1/3 of BASELINE.md).

    The hierarchy is built on the CPU backend (the eager setup path
    compiles one executable per shape; over the axon tunnel that is
    minutes — jit-bucketed device setup is the planned fix) and the
    solve-phase pytree is device_put to the TPU, where the whole
    FGMRES+V-cycle loop runs as one compiled program."""
    cpu = jax.devices("cpu")[0]
    tpu = jax.devices()[0]
    cfg = Config.from_file("configs/FGMRES_AGGREGATION.json")
    with jax.default_device(cpu):
        A = amgx.gallery.poisson("7pt", n, n, n).init()
        b = jnp.ones(A.num_rows)
        slv = amgx.create_solver(cfg)
        t0 = time.perf_counter()
        slv.setup(A)
        setup_s = time.perf_counter() - t0
    data = jax.device_put(slv.solve_data(), tpu)
    bt = jax.device_put(b, tpu)
    x0 = jnp.zeros_like(bt)
    fn = jax.jit(slv._build_solve_fn())
    out = fn(data, bt, x0)
    out[0].block_until_ready()                # compile
    t0 = time.perf_counter()
    x, iters, conv, rn, n0, _ = fn(data, bt, x0)
    x.block_until_ready()
    solve_s = time.perf_counter() - t0
    return setup_s, solve_s, int(iters), bool(conv), \
        float(np.max(np.asarray(rn)) / np.max(np.asarray(n0)))


def main():
    amgx.initialize()
    extra = {}
    spmv_gbps, spmv_s = bench_spmv()
    extra["spmv_7pt_128^3_f32_gbps"] = round(spmv_gbps, 2)
    extra["spmv_7pt_128^3_f32_ms"] = round(spmv_s * 1e3, 4)
    try:
        ceiling = bench_stream_ceiling()
        extra["stream_ceiling_gbps"] = round(ceiling, 2)
        extra["spmv_vs_ceiling"] = round(spmv_gbps / max(ceiling, 1e-9), 3)
    except Exception as e:  # pragma: no cover - bench robustness
        extra["stream_ceiling_error"] = str(e)[:120]
    try:
        setup_s, solve_s, iters, conv, rel = bench_fgmres_amg()
        extra.update({
            "fgmres_agg_32^3_setup_s": round(setup_s, 3),
            "fgmres_agg_32^3_solve_s": round(solve_s, 4),
            "fgmres_agg_32^3_iters": iters,
            "fgmres_agg_32^3_converged": conv,
            "fgmres_agg_32^3_rel_residual": rel,
        })
        value = solve_s
        metric = "poisson7pt_32^3 FGMRES+AggAMG solve wall-clock"
        unit = "s"
    except Exception as e:  # pragma: no cover - bench robustness
        extra["fgmres_error"] = str(e)[:200]
        value = spmv_s * 1e3
        metric = "poisson7pt_128^3 SpMV"
        unit = "ms"
    print(json.dumps({
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": round(spmv_gbps / A100_HBM_GBPS, 4),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
